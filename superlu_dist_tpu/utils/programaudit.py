"""Runtime program auditor — the SLU111/SLU112/SLU114 twin
(``SLU_TPU_VERIFY_PROGRAMS=1``, registered knob).

Executors submit every jitted program ONCE at construction/AOT-stage
time (stream/mega factor kernels, the fused ``make_factor_fn`` program,
the ``solve/device.py`` sweep kernels); the auditor traces it abstractly
(ShapeDtypeStructs — no device work, no compile), walks the closed
jaxpr against the program rules in ``analysis/rules_program.py``, and
raises a structured :class:`ProgramAuditError` (flight-recorder
postmortem at construction) BEFORE the program ever runs — the
"verify before it deadlocks/OOMs" discipline of SLU106/SLU109, moved to
program-construction time.  Clean programs feed their donation-coverage
and baked-const-bytes stats into the compile census
(``obs/compilestats.py`` — surfaced in the ``stats.compile`` block and
the bench row) plus the ``slu_program_audit_total`` metric.

Off path (knob unset): :func:`get_auditor` returns ``None`` without
allocating ANY auditor state — one env read per build site, nothing
else (asserted by ``scripts/check_verify_overhead.py``).

The v5 precision twin (``SLU_TPU_VERIFY_DTYPES=1``) rides the same
``maybe_audit`` hook with its own singleton: every submitted program is
additionally walked for narrowing converts and un-pinned accumulation
dtypes (SLU115/SLU116, ``analysis/rules_precision.py``) and a finding
raises :class:`PrecisionAuditError` before the program runs.  The two
knobs are independent — either, both, or neither; census notes are keyed
``label#dtypes`` so the program-audit coverage accounting never double-
counts, and the off path allocates nothing, same contract.

The v6 sharding twin (``SLU_TPU_VERIFY_SHARDING=1``, or implied by a
positive ``SLU_TPU_MEM_BUDGET_BYTES``) is the third leg: every
submitted program is walked for implicit replication/reshard blowup
(SLU119) and priced by the static peak-memory model (SLU121,
``analysis/rules_sharding.py``); an SLU121 budget breach raises
:class:`MemoryBudgetError` (naming the program — for the mega executor,
the offending bucket rung), any other finding
:class:`ShardingAuditError`, both before the program runs.  Census
notes are keyed ``label#sharding`` and carry ``peak_bytes_est`` /
``replicated_bytes`` — the memory column of the compile census.
"""

from __future__ import annotations

from superlu_dist_tpu.utils.options import env_flag, env_int

#: SLU111 only flags dead-but-not-donated inputs at least this large —
#: small scalars/index vectors are not the peak-memory axis
DONATE_MIN_BYTES = 1 << 20
#: SLU112 flags baked consts at least this large — trace-time scalars
#: (thresholds, iota tables) are not the per-matrix-capture pattern
CONST_MAX_BYTES = 1 << 18

#: SLU119 only prices gathers/replications at least this large — a
#: replicated scalar threshold or index vector is not the OOM axis
RESHARD_MIN_BYTES = 1 << 20

_AUDITOR = None
_DTYPE_AUDITOR = None
_SHARDING_AUDITOR = None


def get_auditor():
    """The process-wide auditor, or None (allocating nothing) when
    ``SLU_TPU_VERIFY_PROGRAMS`` is off."""
    global _AUDITOR
    if not env_flag("SLU_TPU_VERIFY_PROGRAMS"):
        return None
    if _AUDITOR is None:
        _AUDITOR = ProgramAuditor()
    return _AUDITOR


def get_dtype_auditor():
    """The process-wide PRECISION auditor, or None (allocating nothing)
    when ``SLU_TPU_VERIFY_DTYPES`` is off."""
    global _DTYPE_AUDITOR
    if not env_flag("SLU_TPU_VERIFY_DTYPES"):
        return None
    if _DTYPE_AUDITOR is None:
        _DTYPE_AUDITOR = DtypeAuditor()
    return _DTYPE_AUDITOR


def get_sharding_auditor():
    """The process-wide SHARDING/MEMORY auditor, or None (allocating
    nothing) when both ``SLU_TPU_VERIFY_SHARDING`` and
    ``SLU_TPU_MEM_BUDGET_BYTES`` are off — a positive byte budget
    implies the audit without the flag."""
    global _SHARDING_AUDITOR
    budget = env_int("SLU_TPU_MEM_BUDGET_BYTES")
    if not env_flag("SLU_TPU_VERIFY_SHARDING") and budget <= 0:
        return None
    if _SHARDING_AUDITOR is None:
        _SHARDING_AUDITOR = ShardingAuditor(budget_bytes=budget)
    return _SHARDING_AUDITOR


def _reset() -> None:
    """Test hygiene: drop the singletons so a knob flip re-latches."""
    global _AUDITOR, _DTYPE_AUDITOR, _SHARDING_AUDITOR
    _AUDITOR = None
    _DTYPE_AUDITOR = None
    _SHARDING_AUDITOR = None


def find_build_site(site: str) -> str | None:
    """Best-effort source location of a build site like
    ``stream._kernel`` via the existing slulint callgraph — used to name
    the CAPTURING call site in SLU112 reports.  Only runs on the error
    path (it parses the package), never on clean audits."""
    import os
    try:
        from superlu_dist_tpu.analysis.callgraph import build_project
        from superlu_dist_tpu.analysis.core import read_sources
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        fn_name = site.rsplit(".", 1)[-1]
        proj = build_project(read_sources([pkg]))
        for qname, fi in proj.functions.items():
            if qname.rsplit(".", 1)[-1] == fn_name:
                return f"{fi.path}:{fi.node.lineno}"
    except Exception:
        pass
    return None


class ProgramAuditor:
    """Audits each (site, label) program once; results memoized so the
    lazy build paths (stream kernels compile inside their first
    dispatch) pay one trace per distinct program, ever."""

    def __init__(self, donate_min_bytes: int = DONATE_MIN_BYTES,
                 const_max_bytes: int = CONST_MAX_BYTES):
        self.donate_min_bytes = int(donate_min_bytes)
        self.const_max_bytes = int(const_max_bytes)
        self.audited: dict = {}     # (site, label) -> stats dict
        self.findings: list = []    # every finding ever raised (evidence)

    def submit(self, site: str, label: str, fn, args, *, dead=(),
               donated=None, mesh_axes=()) -> dict:
        """Trace + audit one program; raises ProgramAuditError on any
        finding, returns the stats dict when clean.  ``dead`` declares
        the argnums the CALL SITE treats as dead after the call (the
        liveness fact the jaxpr cannot know); ``donated`` overrides the
        auto-detected donation flags (rarely needed)."""
        key = (site, label)
        hit = self.audited.get(key)
        if hit is not None:
            return hit
        from superlu_dist_tpu.analysis.program import audit_spec, trace_spec
        spec = trace_spec(fn, args, label=label, site=site, dead=dead,
                          donated=donated, mesh_axes=mesh_axes)
        findings, stats = audit_spec(spec, self.donate_min_bytes,
                                     self.const_max_bytes)
        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        COMPILE_STATS.audit_note(site, label, stats)
        from superlu_dist_tpu.obs.metrics import get_metrics
        m = get_metrics()
        if m.enabled:
            m.inc("slu_program_audit_total", 1.0, site=site,
                  result="finding" if findings else "clean")
        if findings:
            self.findings.extend(findings)
            if any(f.rule == "SLU112" for f in findings):
                src = find_build_site(site)
                if src:
                    for f in findings:
                        if f.rule == "SLU112":
                            f.message += (f" (capturing build site: "
                                          f"{src})")
            from superlu_dist_tpu.utils.errors import ProgramAuditError
            raise ProgramAuditError(site=site, program=label,
                                    findings=findings)
        self.audited[key] = stats
        return stats


class DtypeAuditor:
    """The SLU115/SLU116 precision twin: audits each (site, label)
    program once for narrowing converts and un-pinned accumulation
    dtypes, memoized like :class:`ProgramAuditor`.  Separate singleton
    so either knob works alone (both on double-traces each program — an
    accepted one-time cost at construction)."""

    def __init__(self):
        self.audited: dict = {}     # (site, label) -> stats dict
        self.findings: list = []    # every finding ever raised (evidence)

    def submit(self, site: str, label: str, fn, args, *, dead=(),
               donated=None, mesh_axes=()) -> dict:
        """Trace + precision-audit one program; raises
        PrecisionAuditError on any finding, returns the stats dict when
        clean."""
        key = (site, label)
        hit = self.audited.get(key)
        if hit is not None:
            return hit
        from superlu_dist_tpu.analysis.program import (audit_dtypes,
                                                       trace_spec)
        spec = trace_spec(fn, args, label=label, site=site, dead=dead,
                          donated=donated, mesh_axes=mesh_axes)
        findings, stats = audit_dtypes(spec)
        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        # keyed off the program label so the SLU111 coverage accounting
        # (audit_block counts programs = len(notes)) never double-counts
        COMPILE_STATS.audit_note(site, f"{label}#dtypes", stats)
        from superlu_dist_tpu.obs.metrics import get_metrics
        m = get_metrics()
        if m.enabled:
            m.inc("slu_precision_audit_total", 1.0, site=site,
                  result="finding" if findings else "clean")
        if findings:
            self.findings.extend(findings)
            from superlu_dist_tpu.utils.errors import PrecisionAuditError
            raise PrecisionAuditError(site=site, program=label,
                                      findings=findings)
        self.audited[key] = stats
        return stats


class ShardingAuditor:
    """The v6 sharding/memory twin: audits each (site, label) program
    once for implicit replication/reshard blowup (SLU119) and prices it
    against the static peak-memory budget (SLU121), memoized like
    :class:`DtypeAuditor`.  Separate singleton so any knob subset works
    alone (each active twin re-traces the program once at construction —
    an accepted one-time cost)."""

    def __init__(self, reshard_min_bytes: int = RESHARD_MIN_BYTES,
                 budget_bytes: int = 0):
        self.reshard_min_bytes = int(reshard_min_bytes)
        self.budget_bytes = int(budget_bytes)
        self.audited: dict = {}     # (site, label) -> stats dict
        self.findings: list = []    # every finding ever raised (evidence)

    def submit(self, site: str, label: str, fn, args, *, dead=(),
               donated=None, mesh_axes=()) -> dict:
        """Trace + sharding/memory-audit one program; raises
        MemoryBudgetError on an SLU121 budget breach, ShardingAuditError
        on any other finding, returns the stats dict when clean."""
        key = (site, label)
        hit = self.audited.get(key)
        if hit is not None:
            return hit
        from superlu_dist_tpu.analysis.program import (audit_sharding,
                                                       trace_spec)
        spec = trace_spec(fn, args, label=label, site=site, dead=dead,
                          donated=donated, mesh_axes=mesh_axes)
        findings, stats = audit_sharding(spec, self.reshard_min_bytes,
                                         self.budget_bytes)
        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        # keyed off the program label so the SLU111 coverage accounting
        # (audit_block counts programs = len(notes)) never double-counts
        COMPILE_STATS.audit_note(site, f"{label}#sharding", stats)
        from superlu_dist_tpu.obs.metrics import get_metrics
        m = get_metrics()
        if m.enabled:
            m.inc("slu_sharding_audit_total", 1.0, site=site,
                  result="finding" if findings else "clean")
        if findings:
            self.findings.extend(findings)
            from superlu_dist_tpu.utils.errors import (MemoryBudgetError,
                                                       ShardingAuditError)
            if any(f.rule == "SLU121" for f in findings):
                raise MemoryBudgetError(
                    site=site, program=label, findings=findings,
                    peak_bytes=stats.get("peak_bytes_est", 0),
                    budget_bytes=self.budget_bytes)
            raise ShardingAuditError(site=site, program=label,
                                     findings=findings)
        self.audited[key] = stats
        return stats


def maybe_audit(site: str, label: str, fn, args, *, dead=(),
                donated=None, mesh_axes=()) -> dict | None:
    """One-line build-site hook: no-op (no state) when every knob is
    off.  Runs the SLU111/112/114 auditor first, then the precision
    twin, then the v6 sharding/memory twin; each memoizes
    independently."""
    aud = get_auditor()
    out = None
    if aud is not None:
        out = aud.submit(site, label, fn, args, dead=dead,
                         donated=donated, mesh_axes=mesh_axes)
    daud = get_dtype_auditor()
    if daud is not None:
        stats = daud.submit(site, label, fn, args, dead=dead,
                            donated=donated, mesh_axes=mesh_axes)
        out = out if out is not None else stats
    saud = get_sharding_auditor()
    if saud is not None:
        stats = saud.submit(site, label, fn, args, dead=dead,
                            donated=donated, mesh_axes=mesh_axes)
        out = out if out is not None else stats
    return out
