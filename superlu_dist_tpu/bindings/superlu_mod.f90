! Fortran interface to the TPU-native SuperLU_DIST framework.
!
! Capability analog of the reference's handle-based Fortran-90 wrapper
! (FORTRAN/superlu_mod.f90 + superlu_c2f_dwrap.c): thin ISO_C_BINDING
! interfaces over the C API in slu_tpu.h.  Matrices are CSR with int64
! indices; B/X are column-major n x nrhs, as a Fortran caller lays them
! out naturally.
!
! Usage:
!   use superlu_tpu
!   info = slu_tpu_init(c_null_char)
!   info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, nrhs)
! Link against libslu_tpu.so (bindings/build.py) and the embedded-python
! libs: $(python3-config --embed --ldflags).

module superlu_tpu
  use iso_c_binding
  implicit none

  interface
     integer(c_int) function slu_tpu_init(backend) bind(C, name="slu_tpu_init")
       import :: c_int, c_char
       character(kind=c_char), dimension(*) :: backend
     end function slu_tpu_init

     integer(c_int) function slu_tpu_solve(n, nnz, indptr, indices, values, &
          b, x, nrhs) bind(C, name="slu_tpu_solve")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: n, nnz, nrhs
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values, b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve

     integer(c_int) function slu_tpu_factor(n, nnz, indptr, indices, values, &
          handle) bind(C, name="slu_tpu_factor")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: n, nnz
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values
       integer(c_int64_t) :: handle
     end function slu_tpu_factor

     integer(c_int) function slu_tpu_solve_factored(handle, n, b, x, nrhs) &
          bind(C, name="slu_tpu_solve_factored")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: handle, n, nrhs
       real(c_double), dimension(*) :: b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve_factored

     integer(c_int) function slu_tpu_free_handle(handle) &
          bind(C, name="slu_tpu_free_handle")
       import :: c_int, c_int64_t
       integer(c_int64_t), value :: handle
     end function slu_tpu_free_handle

     subroutine slu_tpu_finalize() bind(C, name="slu_tpu_finalize")
     end subroutine slu_tpu_finalize
  end interface
end module superlu_tpu
