"""Per-rank bootstrap for multi-process (multi-host-shaped) drivers.

The role mpiexec + MPI_Init play for the reference's pddrive
(EXAMPLE/pddrive.c:29): each OS process calls `boot(...)` FIRST —
before importing jax anywhere else — to pin the CPU backend, raise the
Gloo collective timeout, join the jax.distributed world, and enable the
persistent compile cache; then `attach_tree(...)` joins the
shared-memory tree domain for the host-side analysis collectives.
Used by examples/pddrive_grid.py and the multihost tests.
"""

from __future__ import annotations

import os
import time


def boot(nproc: int, process_id: int, port: int | str,
         coordinator: str = "localhost"):
    """Initialize this rank's jax runtime for a multi-process mesh run.

    Must run before the first `import jax` elsewhere in the process
    (env vars are read at backend init).  Returns the jax module.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    # a rank still compiling a big kernel must not kill a peer waiting
    # in a Gloo collective (default send timeout 30 min; observed on a
    # 1-core box where every rank compiles the same program serially)
    if "collective_timeout" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_cpu_collective_timeout_seconds=7200")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"{coordinator}:{port}",
        num_processes=int(nproc), process_id=int(process_id))
    # Deliberately NO persistent compile cache here: XLA:CPU AOT
    # entries for programs containing CROSS-PROCESS COLLECTIVES are
    # broken on disk-reload on this image (the loader flags the
    # embedded +prefer-no-scatter/+prefer-no-gather codegen prefs as
    # unsupported "machine features" and the reloaded executable's
    # collective schedule diverges — observed as gloo
    # "preamble.length <= op.nbytes" SIGABRTs).  Reloads are also
    # RACY: a rank that finds a peer's just-written entry loads it
    # while the peer runs its in-memory build, so identical runs
    # pass or die by timing.  Reproduced A/B in round 5: cold run
    # green with zero cpu_aot warnings, warm rerun of the very same
    # test dies in 19 s loading its own entries.  Serial CPU and TPU
    # entries reload fine — only the multi-process tier opts out.
    return jax


def attach_tree(shm: str, nproc: int, rank: int, max_len: int = 4096,
                retries: int = 600, delay: float = 0.1):
    """Join the POSIX-shm tree domain; rank 0 creates, others retry
    until the creator has it up (the MPI_Comm_dup moment)."""
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    if rank == 0:
        return TreeComm(shm, nproc, 0, max_len=max_len, create=True)
    for _ in range(retries):
        try:
            return TreeComm(shm, nproc, rank, max_len=max_len,
                            create=False)
        except OSError:
            time.sleep(delay)
    raise TimeoutError(f"treecomm attach timeout for {shm!r}")
