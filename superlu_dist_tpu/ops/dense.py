"""Dense supernodal kernels — the TPU offload boundary.

This layer replaces the reference's BLAS seam (CBLAS fallback / vendor BLAS
/ cuBLAS, SURVEY.md L1): the panel factorization dger/dtrsm loop
(pdgstrf2_trsm, SRC/pdgstrf2.c:140-318), the U-row triangular solves
(pdgstrs2_omp, :771), and the Schur-complement GEMM
(dSchCompUdt-2Ddynamic.c:566) all become one *batched partial factorization
of padded dense fronts*, vmapped over a level's worth of supernodes and
compiled by XLA onto the MXU.

Everything is static-shape: fronts are padded to bucket sizes (M total, W
pivot columns), with identity columns in the pivot-block padding so the
unpivoted LU passes through them untouched.  Tiny pivots are replaced by
±sqrt(eps)·‖A‖ exactly like the reference's GESP (pdgstrf2.c:218-232,
option ReplaceTinyPivot), and counted.

Layout of a factored front F (M×M, pivot width W, real sizes w ≤ W,
u ≤ M−W):
    F[:W, :W]   packed LU of the diagonal block (unit-lower L11 + U11)
    F[W:, :W]   L21 = A21·U11⁻¹   (real data in rows W..W+u)
    F[:W, W:]   U12 = L11⁻¹·A12
    F[W:, W:]   Schur complement S = A22 − L21·U12 (scattered to the pool)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import solve_triangular

from superlu_dist_tpu.utils.options import env_str

_UNROLL = 16   # panel width factored by the unrolled column loop

# ---------------------------------------------------------------------------
# The GEMM precision ladder (docs/PERFORMANCE.md, throughput ladder).
#
# Every Schur-update GEMM in the factor hot path runs at one named tier,
# ordered fastest/least-accurate first:
#
#   bf16     inputs cast to bfloat16, products accumulated in f32
#            (preferred_element_type pins the accumulator) — the MXU's
#            native rate (~6x the HIGHEST baseline on v5e)
#   default  native inputs, lax.Precision.DEFAULT — single-pass bf16 on
#            TPU (the tensorfloat analog: reduced-mantissa inputs, f32
#            accumulate); identical math to f32 on the CPU backend
#   f32      lax.Precision.HIGH — 3-pass bf16, ~full f32-mantissa products
#   highest  lax.Precision.HIGHEST — 6-pass, the exact-f32 baseline
#
# Reduced tiers are made safe to gamble by the gemm-precision escalation
# rung (drivers/gssvx._escalate): a delivered componentwise BERR above
# the gate refactors the SAME skeleton at the next-higher tier, so the
# fast path is default-on without ever degrading delivered accuracy.
# The resolved tier is threaded as an explicit parameter (like the
# pivot-kernel choice) — cached jitted factories key on it and the env
# read stays in the uncached wrappers (slulint SLU102/SLU104/SLU105).
# ---------------------------------------------------------------------------

GEMM_PREC_LADDER = ("bf16", "default", "f32", "highest")

_TIER_LAX = {"default": lax.Precision.DEFAULT,
             "f32": lax.Precision.HIGH,
             "highest": lax.Precision.HIGHEST}

#: legacy SLU_TPU_PRECISION pass-count names -> ladder tiers (an
#: explicitly-set legacy knob keeps meaning what it always meant)
_LEGACY_TIER_MAP = {"default": "default", "high": "f32",
                    "highest": "highest"}


def gemm_precision(name: str | None = None) -> str:
    """Resolve the Schur-GEMM precision tier.

    ``name`` (an Options.gemm_prec value) wins when given; otherwise the
    registered ``SLU_TPU_GEMM_PREC`` knob, then an explicitly-set legacy
    ``SLU_TPU_PRECISION``, then the ladder default ``"default"`` (the
    tensorfloat-analog fast path — identical math to f32 on CPU).  Read
    only from uncached factory wrappers; the result is part of every
    kernel cache key (slulint SLU105 discipline)."""
    if name is None or not str(name).strip():
        name = env_str("SLU_TPU_GEMM_PREC").strip().lower()
        if not name:
            legacy = env_str("SLU_TPU_PRECISION", default="").strip().lower()
            name = _LEGACY_TIER_MAP.get(legacy, "default")
    name = str(name).strip().lower()
    if name not in GEMM_PREC_LADDER:
        raise ValueError(f"SLU_TPU_GEMM_PREC={name!r} — expected one of "
                         f"{list(GEMM_PREC_LADDER)}")
    return name


def next_gemm_precision(tier: str, backend: str | None = None) -> str | None:
    """The next-higher ladder tier that actually CHANGES the arithmetic
    on ``backend``, or None at the top — the escalation rung's step
    function (drivers/gssvx._escalate).

    XLA:CPU executes every ``lax.Precision`` identically (full f32/f64
    products), so there the only real boundary is the bf16 input cast:
    escalating default→f32→highest on CPU would refactor three times
    for bitwise-identical factors, burning the ladder's rung budget on
    no-ops before the dtype escalation gets its turn."""
    if backend is None:
        backend = jax.default_backend()
    i = GEMM_PREC_LADDER.index(tier)
    if i + 1 >= len(GEMM_PREC_LADDER):
        return None
    if backend == "cpu" and tier != "bf16":
        return None          # default/f32/highest coincide on CPU
    return GEMM_PREC_LADDER[i + 1]


def resolve_gemm_tier(prec: str, dtype) -> str:
    """The tier :func:`gemm` will actually RUN for ``dtype`` operands.

    One degrade exists: complex operands have no bf16 carrier, so the
    ``bf16`` tier resolves to ``default`` instead of silently dropping
    imaginary precision.  Callers that record or escalate the tier
    (kernel spans, the BERR ladder) must report THIS value — a trace
    must never show a tier the arithmetic didn't use."""
    if prec == "bf16" and jnp.issubdtype(jnp.result_type(dtype),
                                         jnp.complexfloating):
        return "default"
    return prec


def gemm(a, b, prec: str = "highest"):
    """One ladder-tier batched matmul: the single matmul wrapper every
    Schur-update GEMM in the factor path (and the blocked-TRSM
    off-diagonal GEMMs, solve/device._trsm) routes through.

    ``preferred_element_type`` is pinned to the accumulator dtype on
    every tier, so reduced-INPUT GEMMs still accumulate at f32 (or the
    operands' own width) — the mixed-precision contract the BERR gate
    assumes.  The bf16 tier casts real inputs to bfloat16 and casts the
    f32-accumulated product back; complex operands degrade per
    :func:`resolve_gemm_tier` (asserted, not silently assumed)."""
    out_dt = jnp.result_type(a.dtype, b.dtype)
    # 16-bit-float factor dtypes still accumulate at f32 — pinning the
    # accumulator to bf16 would be a silent accuracy regression
    acc_dt = (jnp.float32 if out_dt in (jnp.bfloat16, jnp.float16)
              else out_dt)
    tier = resolve_gemm_tier(prec, out_dt)
    if tier == "bf16":
        assert not jnp.issubdtype(out_dt, jnp.complexfloating), \
            "bf16 tier on complex operands must resolve to 'default'"
        r = jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                       precision=lax.Precision.DEFAULT,
                       preferred_element_type=jnp.float32)
        return r.astype(out_dt)
    r = jnp.matmul(a, b, precision=_TIER_LAX[tier],
                   preferred_element_type=acc_dt)
    return r.astype(out_dt) if acc_dt != out_dt else r


def _fix_pivot(piv, thresh):
    """GESP tiny-pivot replacement: piv -> phase(piv)·thresh if |piv|<thresh."""
    ap = jnp.abs(piv)
    safe = jnp.where(ap == 0, jnp.ones_like(ap), ap)
    unit = jnp.where(ap == 0, jnp.ones_like(piv), piv / safe.astype(piv.dtype))
    tiny = ap < thresh
    return jnp.where(tiny, unit * thresh.astype(piv.dtype), piv), tiny.astype(jnp.int32)


def _lu_masked(a, thresh):
    """Unpivoted LU of a small block — scatter-free masked formulation.

    Each step is masked selects + a full-matrix rank-1 update + `where`
    masks: no scatter/dynamic-update ops at all.  That matters twice on
    TPU: (a) masked dense updates vectorize on the VPU where scatters
    serialize, and (b) XLA's SPMD partitioner miscompiles vmapped
    scatter-updates whose minor dim gets sharded (observed jax 0.9.0), so
    the factorization core must stay scatter-free to be mesh-shardable.
    The ~3× extra flops of full-width updates are negligible next to the
    Schur GEMMs.

    Row/column/pivot extraction uses elementwise masked reductions rather
    than one-hot dot products: a dot_general here would route through the
    MXU at default precision (bf16 inputs on TPU), truncating the pivot row
    and the pivot value itself every elimination step.

    Returns (packed LU, tiny: (k,) int32 per-column tiny-pivot flags) —
    per-column so callers can mask out identity-padding columns.
    """
    k = a.shape[0]
    idx = jnp.arange(k)

    def step(i, carry):
        a, flags = carry
        sel = idx == i
        e = sel.astype(a.dtype)
        row_i = jnp.sum(a * e[:, None], axis=0)    # row i
        col_i = jnp.sum(a * e[None, :], axis=1)    # column i
        piv_raw = jnp.sum(row_i * e)
        piv, tiny = _fix_pivot(piv_raw, thresh)
        below = (idx > i)
        l = jnp.where(below, col_i / piv, jnp.zeros_like(col_i))
        u = jnp.where(below, row_i, jnp.zeros_like(row_i))   # cols > i
        a = a - l[:, None] * u[None, :]
        # write multipliers + fixed pivot into column i
        new_col = jnp.where(below, l, col_i) + (piv - piv_raw) * e
        cur_col = jnp.sum(a * e[None, :], axis=1)
        a = a + (new_col - cur_col)[:, None] * e[None, :]
        return a, flags + tiny * sel.astype(jnp.int32)

    return jax.lax.fori_loop(0, k, step, (a, jnp.zeros(k, jnp.int32)))


def lu_nopivot(a, thresh, gemm_prec: str = "highest"):
    """Blocked-recursive unpivoted LU with tiny-pivot replacement.

    Static shapes throughout; the trailing update is a single GEMM per
    recursion level, which is where XLA maps onto the MXU.
    ``gemm_prec`` is the caller-resolved ladder tier (gemm_precision) —
    threaded, never read from env here (slulint SLU102).

    Returns (packed LU, tiny: (n,) int32 per-column tiny-pivot flags).
    """
    n = a.shape[0]
    if n <= _UNROLL:
        return _lu_masked(a, thresh)
    h = max(_UNROLL, (n // 2 + _UNROLL - 1) // _UNROLL * _UNROLL)
    h = min(h, n - 1)
    a11, a12 = a[:h, :h], a[:h, h:]
    a21, a22 = a[h:, :h], a[h:, h:]
    f11, c1 = lu_nopivot(a11, thresh, gemm_prec)
    u12 = solve_triangular(f11, a12, lower=True, unit_diagonal=True)
    l21 = solve_triangular(f11, a21.T, trans=1, lower=False).T
    s = a22 - gemm(l21, u12, gemm_prec)
    f22, c2 = lu_nopivot(s, thresh, gemm_prec)
    top = jnp.concatenate([f11, u12], axis=1)
    bot = jnp.concatenate([l21, f22], axis=1)
    return jnp.concatenate([top, bot], axis=0), jnp.concatenate([c1, c2])


_PANEL_BLOCK = 128   # outer panel width of the blocked right-looking LU


def pivot_kernel() -> str:
    """Resolve SLU_TPU_PIVOT_KERNEL (validated like _precision).  Read at
    trace time — executors bake the choice into their cached programs, so
    callers that cache jitted kernels must include this name in their
    cache key (stream._kernel, factor.get_executor do)."""
    name = env_str("SLU_TPU_PIVOT_KERNEL").strip().lower()
    if name not in ("blocked", "recursive"):
        raise ValueError(f"SLU_TPU_PIVOT_KERNEL={name!r} — expected "
                         f"'blocked' or 'recursive'")
    return name


def _blocked_partial_factor(f, thresh, w, gemm_prec: str = "highest"):
    """Right-looking blocked partial LU of one front — compile-bounded.

    The recursive formulation (lu_nopivot) emits O(w/16) distinct
    triangular_solve/GEMM shapes; the TPU compiler takes minutes per
    kernel on wide panels (w ≥ 400 observed >8 min through the remote
    tunnel), which round 2 hit as the "compile wall" (BENCH_r02 null).
    This version is the classic blocked getrf as ONE fori_loop whose body
    has a single static shape: eliminate a PB-wide panel with masked
    rank-1 steps, one (PB,PB)⁻¹·(PB,M) unit-lower triangular solve for
    the U rows, one (M,PB)×(PB,M) trailing GEMM — the MXU-shaped k=PB
    update that carries all the flops (the reference's aggregated Schur
    GEMM, dSchCompUdt-2Ddynamic.c:566-578, fused with the panel factor).
    Compile cost is O(1) in w; executed flops ≈ 2·M²·w (full-width
    trailing updates — the masked-padding trade noted in _lu_masked).

    Columns j ≥ w and identity-padding columns behave as unit pivots with
    zero multipliers, so the loop runs a static ceil(w/PB) panels and the
    final matrix carries packed LU in [:w,:w], L21 below, U12 right, and
    the Schur complement in [w:,w:] — same layout as partial_front_factor.

    NOTE: uses dynamic_slice/dynamic_update_slice on the column axis, so
    it must NOT be used with a column-sharded front (XLA SPMD handles
    that poorly); group_partial_factor keeps the recursive path when
    shardings are requested.

    Returns (packed front (M_ext→M, M), tiny flags (w,)).
    """
    m = f.shape[0]
    pb = min(_PANEL_BLOCK, -(-w // 16) * 16)
    nsteps = -(-w // pb)
    # shrink the panel so nsteps*pb hugs w: e.g. w=136 would otherwise
    # run 2×128 panels and pad the front to 256 columns — up to ~4× the
    # area in solves/GEMMs for wide-pivot small-U buckets
    pb = -(-(-(-w // nsteps)) // 16) * 16
    nsteps = -(-w // pb)
    m_ext = max(m, nsteps * pb)
    if m_ext > m:
        # zero padding; padded columns are never eliminated (j >= w ->
        # inactive) and padded rows stay zero throughout
        f = jnp.pad(f, ((0, m_ext - m), (0, m_ext - m)))
    rows = jnp.arange(m_ext)
    cols_pb = jnp.arange(pb)
    zero = jnp.zeros((), f.dtype)
    one = jnp.ones((), f.dtype)

    def inner(jj, carry):
        panel, flags, j0 = carry
        j = j0 + jj                                   # global column
        active = (j < w)
        col = lax.dynamic_index_in_dim(panel, jj, axis=1, keepdims=False)
        rowj = lax.dynamic_index_in_dim(panel, j, axis=0, keepdims=False)
        piv_raw = lax.dynamic_index_in_dim(rowj, jj, axis=0, keepdims=False)
        piv, tiny = _fix_pivot(piv_raw, thresh)
        piv = jnp.where(active, piv, one)
        below = rows > j
        l = jnp.where(below & active, col / piv, zero)
        urow = jnp.where((cols_pb > jj) & active, rowj, zero)
        panel = panel - l[:, None] * urow[None, :]
        # write the multipliers + fixed pivot back into column jj —
        # inactive columns (j >= w: Schur region / identity padding) keep
        # their values untouched
        newcol = jnp.where(active,
                           jnp.where(below, l, col)
                           + (piv - piv_raw) * (rows == j), col)
        e = (cols_pb == jj).astype(f.dtype)
        cur = lax.dynamic_index_in_dim(panel, jj, axis=1, keepdims=False)
        panel = panel + (newcol - cur)[:, None] * e[None, :]
        flags = flags + tiny * active.astype(jnp.int32) * (
            jnp.arange(w) == j).astype(jnp.int32)
        return panel, flags, j0

    def outer(p, carry):
        a, flags = carry
        j0 = p * pb
        panel = lax.dynamic_slice(a, (0, j0), (m_ext, pb))
        panel, flags, _ = lax.fori_loop(0, pb, inner, (panel, flags, j0))
        a = lax.dynamic_update_slice(a, panel, (0, j0))
        # U rows: solve unit-L11 against the columns right of the panel
        l11 = lax.dynamic_slice(panel, (j0, 0), (pb, pb))
        rtop = lax.dynamic_slice(a, (j0, 0), (pb, m_ext))
        right = rows[None, :] >= j0 + pb              # (1, m_ext) col mask
        u12 = solve_triangular(l11, jnp.where(right, rtop, zero),
                               lower=True, unit_diagonal=True)
        rowact = (j0 + jnp.arange(pb)) < w            # pivot rows only
        u12 = jnp.where(rowact[:, None] & right, u12, zero)
        a = lax.dynamic_update_slice(
            a, jnp.where(rowact[:, None] & right, u12, rtop), (j0, 0))
        # trailing update: every non-pivot row — rows below the panel AND
        # Schur rows (>= w) that fall inside the panel's row range —
        # against all columns to the right
        lpan = jnp.where(((rows >= j0 + pb) | (rows >= w))[:, None],
                         panel, zero)
        a = a - gemm(lpan, u12, gemm_prec)
        return a, flags

    a, flags = lax.fori_loop(0, nsteps, outer,
                             (f, jnp.zeros(w, jnp.int32)))
    return a[:m, :m], flags


def partial_front_factor(f, thresh, w, gemm_prec: str = "highest"):
    """Factor the leading w columns of one front; see module docstring."""
    m = f.shape[0]
    f11, count = lu_nopivot(f[:w, :w], thresh, gemm_prec)
    if w == m:
        return f11, count
    u12 = solve_triangular(f11, f[:w, w:], lower=True, unit_diagonal=True)
    l21 = solve_triangular(f11, f[w:, :w].T, trans=1, lower=False).T
    s = f[w:, w:] - gemm(l21, u12, gemm_prec)
    top = jnp.concatenate([f11, u12], axis=1)
    bot = jnp.concatenate([l21, s], axis=1)
    return jnp.concatenate([top, bot], axis=0), count


def group_partial_factor(fronts, thresh, w, front_sharding=None,
                         pivot_sharding=None, pivot="blocked",
                         gemm_prec="highest"):
    """Partial factorization of a batch of fronts with explicit shardings.

    Group-level formulation of partial_front_factor: the pivot-block LU is
    latency-bound (unrolled column loop) and runs replicated along the
    "panel" mesh axis (pivot_sharding), while the trailing triangular
    solves and the Schur GEMM — where the flops are (reference
    dSchCompUdt-2Ddynamic.c:566) — are pure batched matmuls that partition
    cleanly over the 2D mesh (front_sharding).  Note: the scatter-style
    pivot loop must NOT be sharded along its last dim — XLA's SPMD
    partitioner miscompiles vmapped scatter-updates with a sharded minor
    dimension (observed on jax 0.9.0), and splitting a tiny LU across
    chips would be latency-dominated anyway.

    Returns (lpanel (B,m,w), upanel (B,w,u), schur (B,u,u), tiny (B,w)).
    lpanel stacks the packed diagonal block (L11 unit-lower + U11) over
    L21; upanel is U12.  The Schur block is returned separately — the
    caller scatters it into the update pool and then drops it, so the
    stored factors are only the n_L + n_U panels the solves read (the
    reference likewise keeps L in Lnzval_bc_ptr and U in Unzval_br_ptr and
    never stores the eliminated A22, superlu_ddefs.h:97-183).
    """
    from jax.lax import with_sharding_constraint as wsc
    m = fronts.shape[-1]
    b = fronts.shape[0]
    # `pivot`/`gemm_prec` are the caller-resolved SLU_TPU_PIVOT_KERNEL /
    # SLU_TPU_GEMM_PREC choices: this function runs inside cached jitted
    # factories, so the env reads must happen in the (uncached) factory
    # wrappers that put both in their cache keys — never here at trace
    # time (slulint SLU105)
    if (front_sharding is None and pivot_sharding is None
            and pivot == "blocked"):
        # unsharded: the compile-bounded blocked kernel (see
        # _blocked_partial_factor).  Sharded runs keep the recursive
        # path — its scatter-free masked core is what the SPMD
        # partitioner handles.
        packed, tiny = jax.vmap(
            lambda x: _blocked_partial_factor(x, thresh, w,
                                              gemm_prec))(fronts)
        return (packed[:, :, :w], packed[:, :w, w:],
                packed[:, w:, w:], tiny)
    f11_in = fronts[:, :w, :w]
    if pivot_sharding is not None:
        f11_in = wsc(f11_in, pivot_sharding)
    f11, tiny = jax.vmap(lambda x: lu_nopivot(x, thresh, gemm_prec))(f11_in)
    if w == m:
        if pivot_sharding is not None:
            f11 = wsc(f11, pivot_sharding)
        u = 0
        return f11, jnp.zeros((b, w, u), fronts.dtype), \
            jnp.zeros((b, u, u), fronts.dtype), tiny
    a12 = fronts[:, :w, w:]
    a21 = fronts[:, w:, :w]
    a22 = fronts[:, w:, w:]
    u12 = jax.vmap(lambda l, b_: solve_triangular(l, b_, lower=True,
                                                  unit_diagonal=True))(f11, a12)
    l21 = jax.vmap(lambda u_, b_: solve_triangular(u_, b_.T, trans=1,
                                                   lower=False).T)(f11, a21)
    s = a22 - gemm(l21, u12, gemm_prec)
    if front_sharding is not None:
        s = wsc(s, front_sharding)
    lpanel = jnp.concatenate([f11, l21], axis=1)
    if front_sharding is not None:
        lpanel = wsc(lpanel, front_sharding)
    return lpanel, u12, s, tiny


def make_front_kernel(m: int, w: int, dtype: str):
    """Jitted batched front factorization for bucket shape (M=m, W=w).

    Returns fn(F: (B, m, m), thresh) -> (F_packed: (B, m, m), tiny: int32).
    Cached per (m, w, dtype, pivot kernel, gemm tier); batch size
    participates in jit's own cache.  Honors SLU_TPU_PIVOT_KERNEL and
    SLU_TPU_GEMM_PREC like the executors.
    """
    return _make_front_kernel(m, w, dtype, pivot_kernel(), gemm_precision())


@functools.lru_cache(maxsize=None)
def _make_front_kernel(m: int, w: int, dtype: str, pivot: str,
                       gemm_prec: str = "highest"):
    if pivot == "blocked":
        def kernel(fronts, thresh):
            outs, flags = jax.vmap(
                lambda f: _blocked_partial_factor(f, thresh, w,
                                                  gemm_prec))(fronts)
            return outs, jnp.sum(flags)
    else:
        def kernel(fronts, thresh):
            outs, counts = jax.vmap(
                lambda f: partial_front_factor(f, thresh, w,
                                               gemm_prec))(fronts)
            return outs, jnp.sum(counts)

    return jax.jit(kernel)
