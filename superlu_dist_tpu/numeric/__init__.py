from superlu_dist_tpu.numeric.plan import FactorPlan, build_plan
from superlu_dist_tpu.numeric.factor import numeric_factorize, NumericFactorization
