"""slulint v6 sharding & memory-flow rules — SLU119-SLU122.

Landing AHEAD of ROADMAP item 1 (the shard_map/pjit SPMD rewrite), the
way SLU114 landed ahead of it in PR 13: the two failure classes SLU114
does NOT cover are exactly the ones that kill real SPMD solver ports —
silent full-replication/resharding inserted by the partitioner (an
implicit all-gather of a Schur pool is a pod-slice OOM), and padded-rung
buffer sizing whose peak live bytes exceed per-device HBM.

Two rules run over TRACED PROGRAMS (closed jaxprs, via
``analysis/program.py`` and the ``SLU_TPU_VERIFY_SHARDING=1`` runtime
twin in ``utils/programaudit.py``):

SLU119 — implicit replication/reshard blowup.  A gathering collective
(``all_gather``/``all_to_all``) whose output is at least the byte
threshold, or an explicit sharding constraint/transfer that resolves to
a FULLY-REPLICATED layout on a non-trivial mesh, moves (or duplicates)
whole-buffer traffic the author probably never asked for: under GSPMD a
single underconstrained op makes the partitioner insert exactly these —
and a replicated Schur pool is the device-memory-constrained assembly
problem of arXiv:2509.21037.  Findings name the op, the axes, and the
bytes; stats carry ``replicated_bytes``/``resharded_bytes`` for the
census.

SLU121 — static peak-memory model.  A forward liveness walk over the
closed jaxpr computes the high-water live-byte mark (arguments + baked
consts + intermediates, each freed after its last use; sub-jaxpr bodies
contribute their own transient peak).  The estimate is surfaced as
``peak_bytes_est`` in the compile census and bench rows, and — when
``SLU_TPU_MEM_BUDGET_BYTES`` is set — a program whose peak exceeds the
budget FAILS before it runs (``MemoryBudgetError``), naming the largest
live buffers.  The model is deliberately sharding-blind (per-device
bytes = global bytes): it upper-bounds a single-device run and exactly
bounds the replicated path, which is what the mega executor's
padded-rung pool sizing needs.

Two rules run over SOURCE (part of the slulint CLI rule set):

SLU120 — mesh/spec hygiene.  shard_map/pjit/Mesh/NamedSharding/
PartitionSpec call sites must spell axis names declared in the central
registry (``utils/meshreg.py`` — the axis-name analog of SLU104's knob
registry): a typo'd axis is not an error anywhere in jax, the dimension
just silently replicates.  Literal ``in_specs`` tuples must match the
wrapped function's positional arity, and args donated through
``jax.jit(shard_map(...), donate_argnums=...)`` must carry a
``P(...)`` spec — donating a spec-less (replicated) arg aliases a
buffer every device still reads.

SLU122 — cross-mesh transfer in dispatch loops.  Extends the SLU113
device-taint: ``jax.device_put`` / ``.reshard`` of a DEVICE value
inside a per-group For/While dispatch loop in numeric//solve/ is a
whole-buffer cross-device (or cross-layout) copy once per group — the
reshard analog of SLU113's host round-trip.  Host-side uploads
(numpy -> device) are exempt: the taint gate only fires when the value
already lives on a device.
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Finding, Rule, dotted_name
from superlu_dist_tpu.analysis.dataflow import TAINT_DEVICE, FnFlow
from superlu_dist_tpu.analysis.program import (ProgramSpec, aval_bytes,
                                               const_bytes, eqn_axes,
                                               iter_eqns, open_jaxpr,
                                               sub_jaxprs)

RULE_IMPLICIT_RESHARD = "SLU119"
RULE_MESH_HYGIENE = "SLU120"
RULE_PEAK_MEMORY = "SLU121"
RULE_LOOP_TRANSFER = "SLU122"

#: primitives that materialize the GATHERED (cross-shard) operand — the
#: implicit-replication traffic SLU119 prices.  ``psum`` and friends
#: reduce (output is shard-shaped), so they are deliberately absent.
GATHERING_PRIMS = frozenset({"all_gather", "all_to_all", "pgather"})

#: primitives that re-lay-out an existing device value
RESHARD_PRIMS = frozenset({"sharding_constraint", "device_put"})


def _program_finding(rule: str, spec: ProgramSpec, message: str,
                     hint: str) -> Finding:
    return Finding(rule, f"<program:{spec.site}[{spec.label}]>", 0, 1,
                   message, hint)


def _eqn_out_bytes(eqn) -> int:
    return sum(aval_bytes(getattr(v, "aval", None))
               for v in getattr(eqn, "outvars", ()))


def _replicated_shardings(eqn):
    """Duck-typed: sharding-like objects in the eqn's params that report
    ``is_fully_replicated`` truthy (NamedSharding/GSPMDSharding both
    carry the flag; stubs only need the attribute)."""
    out = []
    for v in getattr(eqn, "params", {}).values():
        for s in (v if isinstance(v, (list, tuple)) else (v,)):
            rep = getattr(s, "is_fully_replicated", None)
            if rep:
                out.append(s)
    return out


# --------------------------------------------------------------------------
# SLU119 — implicit replication / reshard blowup (jaxpr rule)
# --------------------------------------------------------------------------

def audit_resharding(spec: ProgramSpec, min_bytes: int):
    """Findings for gathering collectives and fully-replicated reshard
    constraints moving >= min_bytes, plus {replicated_bytes,
    resharded_bytes, n_gathers}."""
    findings = []
    replicated = 0
    resharded = 0
    n_gathers = 0
    for eqn in iter_eqns(spec.jaxpr):
        name = getattr(eqn.primitive, "name", str(eqn.primitive))
        if name in GATHERING_PRIMS:
            n_gathers += 1
            nb = _eqn_out_bytes(eqn)
            replicated += nb
            if nb < min_bytes:
                continue
            axes = eqn_axes(eqn) or ("?",)
            findings.append(_program_finding(
                RULE_IMPLICIT_RESHARD, spec,
                f"`{name}` over axis {','.join(map(repr, axes))} "
                f"materializes {nb} gathered bytes on every shard — the "
                "implicit-replication blowup (a gathered Schur pool is a "
                "pod-slice OOM, not a slowdown)",
                "keep the operand shard-resident: reduce with psum/"
                "psum_scatter, or reshard only the panel actually "
                "consumed (the partitioner inserts gathers wherever an "
                "op is underconstrained — constrain it)"))
        elif name in RESHARD_PRIMS:
            nb = _eqn_out_bytes(eqn)
            resharded += nb
            reps = _replicated_shardings(eqn)
            if not reps or not spec.mesh_axes or nb < min_bytes:
                continue
            replicated += nb
            findings.append(_program_finding(
                RULE_IMPLICIT_RESHARD, spec,
                f"`{name}` resolves {nb} bytes to a FULLY-REPLICATED "
                f"layout on mesh axes {list(spec.mesh_axes)} — every "
                "device holds the whole buffer, so the per-device "
                "footprint stops scaling with the mesh",
                "replicate only below the byte threshold; shard large "
                "buffers over a mesh axis (PartitionSpec) and let the "
                "consumers gather the panel they touch"))
    return findings, {"replicated_bytes": int(replicated),
                      "resharded_bytes": int(resharded),
                      "n_gathers": int(n_gathers)}


# --------------------------------------------------------------------------
# SLU121 — static peak-memory model (jaxpr rule)
# --------------------------------------------------------------------------

def _var_bytes(v) -> int:
    return aval_bytes(getattr(v, "aval", None))


def _is_literal(v) -> bool:
    # jax.core.Literal carries .val; variables do not
    return hasattr(v, "val")


def _jaxpr_peak(j) -> tuple:
    """(peak_bytes, args_bytes, n_eqns) for one OPEN jaxpr body: a
    forward walk where every binder's bytes go live at its defining
    equation and die after its last use (jaxpr binders are SSA, so
    id(var) is a sound key).  Sub-jaxpr-bearing equations contribute
    their body's transient high-water (inner peak minus inner args,
    which the outer operands already count)."""
    invars = list(getattr(j, "constvars", ())) + list(getattr(j,
                                                              "invars", ()))
    eqns = list(getattr(j, "eqns", ()))
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in getattr(eqn, "invars", ()):
            if not _is_literal(v):
                last_use[id(v)] = i
    for v in getattr(j, "outvars", ()):
        if not _is_literal(v):
            last_use[id(v)] = len(eqns)
    args_bytes = sum(_var_bytes(v) for v in invars)
    live = args_bytes
    peak = live
    # args with no use at all die before the first equation
    for v in invars:
        if id(v) not in last_use:
            live -= _var_bytes(v)
    for i, eqn in enumerate(eqns):
        out_b = sum(_var_bytes(v) for v in getattr(eqn, "outvars", ()))
        transient = 0
        for s in sub_jaxprs(eqn):
            inner_peak, inner_args, _ = _jaxpr_peak(s)
            transient = max(transient, inner_peak - inner_args)
        live += out_b
        peak = max(peak, live + transient)
        for v in getattr(eqn, "outvars", ()):
            if id(v) not in last_use:
                live -= _var_bytes(v)
        for vid, bytes_ in _dying_at(eqns[i], last_use, i):
            live -= bytes_
    return peak, args_bytes, len(eqns)


def _dying_at(eqn, last_use, i):
    seen = set()
    for v in getattr(eqn, "invars", ()):
        if _is_literal(v) or id(v) in seen:
            continue
        seen.add(id(v))
        if last_use.get(id(v)) == i:
            yield id(v), _var_bytes(v)


def _top_buffers(j, n: int = 3) -> str:
    sizes = []
    for v in list(getattr(j, "invars", ())) + [
            ov for e in getattr(j, "eqns", ())
            for ov in getattr(e, "outvars", ())]:
        nb = _var_bytes(v)
        if nb:
            aval = getattr(v, "aval", None)
            short = getattr(aval, "str_short", None)
            sizes.append((nb, short() if callable(short) else str(aval)))
    sizes.sort(key=lambda t: -t[0])
    return ", ".join(f"{s} ({nb} B)" for nb, s in sizes[:n]) or "none"


def audit_peak_memory(spec: ProgramSpec, budget_bytes: int):
    """High-water live-byte estimate for one program; a finding when a
    positive budget is exceeded.  Returns (findings, {peak_bytes_est,
    args_bytes, n_eqns})."""
    j = open_jaxpr(spec.jaxpr)
    peak, args_bytes, n_eqns = _jaxpr_peak(j)
    peak += sum(const_bytes(c) for c in getattr(spec.jaxpr, "consts", ()))
    findings = []
    if budget_bytes and budget_bytes > 0 and peak > budget_bytes:
        findings.append(_program_finding(
            RULE_PEAK_MEMORY, spec,
            f"static peak live bytes {peak} exceed the "
            f"SLU_TPU_MEM_BUDGET_BYTES budget of {budget_bytes} "
            f"(largest buffers: {_top_buffers(j)})",
            "shrink the padded rung (SLU_TPU_BUCKET_GROWTH / "
            "SLU_TPU_SCHED_WINDOW), donate dead inputs so XLA aliases "
            "them, or raise the budget — the estimate is "
            "free-after-last-use, so anything above it is structural"))
    return findings, {"peak_bytes_est": int(peak),
                      "args_bytes": int(args_bytes),
                      "n_eqns": int(n_eqns)}


# --------------------------------------------------------------------------
# catalog stubs: SLU119/SLU121 are jaxpr-tier rules with no source half,
# but they need Rule identities so `--rules SLU119,SLU121` selects them,
# `--list-rules` and the SARIF catalog describe them, and suppressions/
# baselines treat their runtime findings uniformly.
# --------------------------------------------------------------------------

class ImplicitReshardRule(Rule):
    rule_id = RULE_IMPLICIT_RESHARD
    title = "implicit-replication-reshard-blowup"
    hint = ("keep large operands shard-resident; the jaxpr walk "
            "(audit_resharding) runs under SLU_TPU_VERIFY_SHARDING=1 — "
            "the source scan has nothing to check")

    def check(self, tree, source, path, project=None):
        return []


class PeakMemoryRule(Rule):
    rule_id = RULE_PEAK_MEMORY
    title = "static-peak-memory-budget"
    hint = ("the liveness walk (audit_peak_memory) runs under "
            "SLU_TPU_VERIFY_SHARDING=1 / SLU_TPU_MEM_BUDGET_BYTES — "
            "the source scan has nothing to check")

    def check(self, tree, source, path, project=None):
        return []


# --------------------------------------------------------------------------
# SLU120 — mesh/spec hygiene (source rule)
# --------------------------------------------------------------------------

_SHARD_MAP_NAMES = frozenset({"shard_map", "jax.experimental.shard_map."
                              "shard_map"})
_PJIT_NAMES = frozenset({"pjit", "jax.pjit"})
_SPEC_CTORS = frozenset({"P", "PartitionSpec"})
_JIT_NAMES = frozenset({"jit", "jax.jit"})


def _is_spec_ctor(name: str) -> bool:
    return name in _SPEC_CTORS or name.endswith(".PartitionSpec")


def _is_mesh_ctor(name: str) -> bool:
    return name == "Mesh" or name.endswith(".Mesh")


def _literal_strings(node):
    """(value, anchor) for every string constant under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value, sub


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _positional_arity(fn_node) -> int | None:
    """Positional parameter count of a def/lambda (None when *args makes
    the arity open)."""
    a = fn_node.args
    if a.vararg is not None:
        return None
    return len(a.posonlyargs) + len(a.args)


class MeshSpecHygieneRule(Rule):
    rule_id = RULE_MESH_HYGIENE
    title = "mesh-spec-hygiene"
    hint = ("declare every mesh axis in utils/meshreg.py and spell it "
            "exactly at shard_map/pjit/Mesh/PartitionSpec call sites — "
            "a typo'd axis silently replicates the dimension")

    def __init__(self):
        self._axes = None

    @property
    def axes(self) -> frozenset:
        if self._axes is None:
            from superlu_dist_tpu.utils.meshreg import registered_axes
            self._axes = frozenset(registered_axes())
        return self._axes

    def check(self, tree, source, path, project=None):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _SHARD_MAP_NAMES or name in _PJIT_NAMES:
                out.extend(self._check_specs(path, node))
                if name in _SHARD_MAP_NAMES:
                    out.extend(self._check_arity(path, node, project))
            elif _is_mesh_ctor(name):
                axes = _kw(node, "axis_names") or (
                    node.args[1] if len(node.args) > 1 else None)
                if axes is not None:
                    out.extend(self._check_names(path, axes, name))
            elif _is_spec_ctor(name):
                out.extend(self._check_names(path, node, name))
            elif name in _JIT_NAMES:
                out.extend(self._check_donation(path, node))
        # a P("typo") inside an in_specs= kwarg is reached by both the
        # spec walk and the ctor walk — one finding per anchor
        seen, uniq = set(), []
        for f in out:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                uniq.append(f)
        return uniq

    def _check_names(self, path, node, what):
        out = []
        for value, anchor in _literal_strings(node):
            if value not in self.axes:
                out.append(self.finding(
                    path, anchor,
                    f"axis name {value!r} in `{what}(...)` is not "
                    "declared in the mesh-axis registry "
                    f"(utils/meshreg.py declares "
                    f"{sorted(self.axes) or 'no axes'}) — jax treats an "
                    "unknown axis as replicated, silently"))
        return out

    def _check_specs(self, path, call):
        out = []
        for spec_kw in ("in_specs", "out_specs"):
            v = _kw(call, spec_kw)
            if v is not None:
                out.extend(self._check_names(path, v,
                                             f"{spec_kw}="))
        return out

    def _check_arity(self, path, call, project):
        """Literal in_specs tuple length vs the wrapped function's
        positional arity (resolvable local defs only)."""
        specs = _kw(call, "in_specs")
        if not isinstance(specs, (ast.Tuple, ast.List)) or not call.args:
            return []
        wrapped = call.args[0]
        arity = None
        if isinstance(wrapped, ast.Lambda):
            arity = _positional_arity(wrapped)
        elif isinstance(wrapped, ast.Name) and project is not None:
            for qname, fi in project.functions.items():
                if fi.path == path and qname.rsplit(".", 1)[-1] == \
                        wrapped.id:
                    arity = _positional_arity(fi.node)
                    break
        if arity is None or arity == len(specs.elts):
            return []
        return [self.finding(
            path, specs,
            f"in_specs declares {len(specs.elts)} spec(s) but the "
            f"wrapped function takes {arity} positional argument(s) — "
            "jax reports this as an opaque tree mismatch at trace time; "
            "the spec list must mirror the signature")]

    def _check_donation(self, path, call):
        """jax.jit(shard_map(...), donate_argnums=...): donated
        positions must carry a P(...) spec, not None/replicated."""
        if not call.args:
            return []
        inner = call.args[0]
        if not (isinstance(inner, ast.Call)
                and dotted_name(inner.func) in _SHARD_MAP_NAMES):
            return []
        specs = _kw(inner, "in_specs")
        donate = _kw(call, "donate_argnums")
        if specs is None or donate is None:
            return []
        if not isinstance(specs, (ast.Tuple, ast.List)):
            return []
        idxs = []
        if isinstance(donate, ast.Constant) and isinstance(donate.value,
                                                           int):
            idxs = [donate.value]
        elif isinstance(donate, (ast.Tuple, ast.List)):
            idxs = [e.value for e in donate.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)]
        out = []
        for i in idxs:
            if i >= len(specs.elts):
                continue
            el = specs.elts[i]
            is_spec = isinstance(el, ast.Call) and _is_spec_ctor(
                dotted_name(el.func))
            if not is_spec:
                out.append(self.finding(
                    path, el,
                    f"donated argument {i} carries no PartitionSpec "
                    "(in_specs element is not a P(...) call) — donating "
                    "a replicated/spec-less buffer aliases storage every "
                    "device still reads",
                    "give donated args an explicit P(...) layout, or "
                    "drop them from donate_argnums"))
        return out


# --------------------------------------------------------------------------
# SLU122 — cross-mesh transfer in dispatch loops (source rule)
# --------------------------------------------------------------------------

_TRANSFER_CALLS = frozenset({"jax.device_put", "device_put"})


class _TransferFlow(FnFlow):
    """FnFlow with the SLU122 in-loop transfer scan attached (the
    device-taint machinery of SLU113's _DispatchFlow, hunting resharding
    instead of host coercions)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.hits: dict = {}     # (line, col) -> (anchor node, message)

    def _device(self, expr) -> str | None:
        t = self.taint(expr)
        return t.get(TAINT_DEVICE)

    def _scan_expr(self, expr) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            prov = None
            what = None
            if name in _TRANSFER_CALLS and node.args:
                prov = self._device(node.args[0])
                what = f"`{name}`"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "reshard":
                prov = self._device(node.func.value)
                what = "`.reshard()`"
            if prov is not None:
                self._hit(node, what, prov)

    def _hit(self, node, what, prov) -> None:
        key = (node.lineno, node.col_offset)
        if key not in self.hits:
            self.hits[key] = (node, f"{what} on a device value ({prov}) "
                              "inside the dispatch loop — a whole-buffer "
                              "cross-device/cross-layout copy once per "
                              "group (the reshard analog of SLU113's "
                              "host round-trip)")

    def visit_stmt(self, st) -> None:
        if self.loop_depth == 0:
            return
        if isinstance(st, (ast.If, ast.While)):
            self._scan_expr(st.test)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_expr(item.context_expr)
            return
        if isinstance(st, ast.Try):
            return
        self._scan_expr(st)


class CrossMeshTransferRule(Rule):
    rule_id = RULE_LOOP_TRANSFER
    title = "cross-mesh-transfer-in-dispatch-loop"
    hint = ("commit buffers to their mesh layout ONCE before the loop "
            "(the __call__-prologue device_put discipline of "
            "stream.__call__/df64_factor.__call__), or keep the reshard "
            "inside the jitted program where XLA can fuse it; host "
            "uploads (numpy -> device) are exempt")
    package_dirs = ("numeric", "solve")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        out = []
        for qname, fi in project.functions.items():
            if fi.path != path:
                continue
            flow = _TransferFlow.for_function(project, fi)
            flow.run()
            for key in sorted(flow.hits):
                node, msg = flow.hits[key]
                out.append(self.finding(path, node, msg))
        return out
