#!/usr/bin/env python
"""Bench-history JSONL DB: append, list, and summarize bench rows.

The bench trajectory has so far lived in loose ``BENCH_r0*.json``
snapshots with no comparison tooling.  This script owns the append-only
JSONL database the perf-regression gate (``check_perf_regress.py``)
reads: one bench JSON row per line, stamped with ``recorded_unix`` and
a derived ``history_key`` so rows are only ever compared within the
same (metric, backend, executor, schedule, blocking) configuration.

Usage:
  scripts/bench_history.py add <row.json | ->      append one bench row
  scripts/bench_history.py list [SUBSTR]           rows (key filter)
  scripts/bench_history.py summary                 per-key min/median/max

DB path: ``SLU_TPU_BENCH_HISTORY`` (registered knob), default
``.cache/bench_history.jsonl`` under the repo (gitignored — the history
is machine-local; rows from different machines are not comparable).
Pure text processing plus the knob registry; no jax import.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from superlu_dist_tpu.utils.options import env_str  # noqa: E402


def history_path() -> str:
    p = env_str("SLU_TPU_BENCH_HISTORY").strip()
    return p or os.path.join(REPO, ".cache", "bench_history.jsonl")


def row_key(row: dict) -> str:
    """The comparability key: rows are baselined only against rows of
    the same metric + backend + executor + GEMM-precision configuration
    (a bf16-ladder row must never be the baseline a highest-tier run is
    judged against, and vice versa — no cross-precision comparisons)."""
    blocking = row.get("blocking")
    return "|".join(str(x) for x in (
        row.get("metric", "?"),
        row.get("backend", "?"),
        row.get("granularity", "?"),
        row.get("schedule", "?"),
        row.get("gemm_precision", "?"),
        ",".join(str(b) for b in blocking) if blocking else "?",
    ))


def load_history(path: str | None = None) -> list:
    path = path or history_path()
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass               # a torn tail line never kills the DB
    except FileNotFoundError:
        pass
    return rows


def append_row(row: dict, path: str | None = None, **extra) -> dict:
    """Stamp + append one row; returns the stamped record."""
    path = path or history_path()
    rec = dict(row)
    rec["recorded_unix"] = round(time.time(), 3)
    rec["history_key"] = row_key(row)
    rec.update(extra)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def _read_row(arg: str) -> dict:
    text = sys.stdin.read() if arg == "-" else open(arg).read()
    lines = [ln for ln in text.strip().splitlines() if ln.strip()]
    return json.loads(lines[-1])       # tolerate bench stderr noise above


def main(argv) -> int:
    if len(argv) < 1 or argv[0] not in ("add", "list", "summary"):
        print(__doc__, file=sys.stderr)
        return 2
    cmd = argv[0]
    if cmd == "add":
        row = _read_row(argv[1] if len(argv) > 1 else "-")
        rec = append_row(row)
        print(f"appended [{rec['history_key']}] value={rec.get('value')} "
              f"-> {history_path()}")
        return 0
    rows = load_history()
    if not rows:
        print(f"no history at {history_path()!r} (seed it with "
              "'bench_history.py add')", file=sys.stderr)
        return 1
    if cmd == "list":
        sub = argv[1] if len(argv) > 1 else ""
        for r in rows:
            key = r.get("history_key", row_key(r))
            if sub and sub not in key:
                continue
            flag = " GATE-FAIL" if r.get("gate_fail") else ""
            print(f"{r.get('recorded_unix', 0):14.0f}  "
                  f"{r.get('value')!s:>8}  "
                  f"compile {r.get('compile_seconds', '?')!s:>8}  "
                  f"[{key}]{flag}")
        return 0
    # summary: per-key distribution of the headline value
    by_key: dict[str, list] = {}
    for r in rows:
        if r.get("value") is None or r.get("gate_fail"):
            continue
        by_key.setdefault(r.get("history_key", row_key(r)), []).append(
            float(r["value"]))
    for key in sorted(by_key):
        vals = by_key[key]
        print(f"{len(vals):4d} rows  min {min(vals):8.2f}  "
              f"median {statistics.median(vals):8.2f}  "
              f"max {max(vals):8.2f}  [{key}]")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
