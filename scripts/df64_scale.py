#!/usr/bin/env python
"""df64 (emulated-double) at scale on a genuinely ill-conditioned system.

Shifts the 3D Poisson operator to A − σI with σ just below the ANALYTIC
λ_min = 6 − 6·cos(π/(nx+1)) (7-pt stencil eigenvalues are
6 − 2Σ cos(k_iπ/(nx+1)) — no dense eigensolve needed at scale), giving
κ ≈ DF64S_KAPPA (default 1e10).  At this conditioning f32 factors +
f64 IR converge on the residual but the SOLUTION is garbage (forward
error ≈ κ·2⁻²⁴ ≫ 1e-3), while df64 factors (~2⁻⁴⁸) recover it — the
SURVEY §7 hard-part-1 story (f64-on-TPU) demonstrated beyond toy size.

Writes docs/df64_scale_n{n}.json.  Env: DF64S_NX (default 16 → n=4096),
DF64S_KAPPA (default 1e10), DF64S_MESH ("RxC", e.g. "4x2": run the df64
factorization over an R×C virtual mesh with the hi/lo Schur pools
PARTITIONED across all its devices — the VERDICT-r3 missing-#4 path to
the n≈1M class — and record the per-device pool share; artifact suffix
_mesh{R}x{C}).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import (REPO, cpu_session, parse_mesh_spec,  # noqa: E402
                     raise_collective_timeouts)


def main():
    # error-free df64 transformations must survive the CPU compiler:
    # fusion re-associates the two-float arithmetic (same recipe as
    # tests/test_df64.py); TPU runs don't need this
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_disable_hlo_passes="
                                 "fusion,cpu-instruction-fusion")
    raise_collective_timeouts()
    mesh_spec = os.environ.get("DF64S_MESH", "1")
    mesh_r, mesh_c, n_dev = parse_mesh_spec(mesh_spec)
    cpu_session(n_devices=n_dev)
    import superlu_dist_tpu as slu
    import superlu_dist_tpu.sparse.formats as fmts
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.parallel.grid import gridinit

    nx = int(os.environ.get("DF64S_NX", "16"))
    kappa = float(os.environ.get("DF64S_KAPPA", "1e10"))
    grid = gridinit(mesh_r, mesh_c) if n_dev > 1 else None

    a0 = poisson3d(nx)
    n = a0.n_rows
    lmin = 6.0 - 6.0 * np.cos(np.pi / (nx + 1))
    lmax = 6.0 + 6.0 * np.cos(np.pi / (nx + 1))
    delta = lmax / (lmin * kappa)
    sigma = lmin * (1.0 - delta)
    rows = np.repeat(np.arange(n), np.diff(a0.indptr))
    vals = a0.data.copy()
    vals[rows == a0.indices] -= sigma
    rng = np.random.default_rng(0)
    is_complex = os.environ.get("DF64S_COMPLEX", "0") == "1"
    if is_complex:
        # unitary diagonal similarity D A D* (D = diag(e^{iθ})): the
        # spectrum — hence κ — is exactly preserved while every entry
        # becomes genuinely complex; the zdf64 twin of the experiment
        # (pzgstrf twin discipline, SRC/pzgstrf.c:243)
        d = np.exp(1j * rng.uniform(0.0, 2 * np.pi, n))
        vals = vals * d[rows] * np.conj(d[a0.indices])
    a = fmts.SparseCSR(n, n, a0.indptr, a0.indices, vals)
    xt = rng.standard_normal(n) + (1j * rng.standard_normal(n)
                                   if is_complex else 0.0)
    b = a.matvec(xt)
    print(f"[df64s] n={n} sigma={sigma:.6f} target kappa={kappa:.1e} "
          f"complex={is_complex}", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    x32, _, _, i32 = slu.gssvx(Options(factor_dtype="float32"), a, b)
    t32 = time.perf_counter() - t0
    e32 = float(np.linalg.norm(x32 - xt) / np.linalg.norm(xt))
    print(f"[df64s] f32+IR {t32:.1f}s forward_err={e32:.2e}",
          file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    xdf, ludf, _, idf = slu.gssvx(
        Options(factor_dtype="df64", pool_partition=grid is not None),
        a, b, grid=grid)
    tdf = time.perf_counter() - t0
    edf = float(np.linalg.norm(xdf - xt) / np.linalg.norm(xt))
    rdf = float(np.linalg.norm(b - a.matvec(xdf)) / np.linalg.norm(b))
    print(f"[df64s] df64 {tdf:.1f}s forward_err={edf:.2e} resid={rdf:.2e}",
          file=sys.stderr, flush=True)

    rec = {"experiment": ("zdf64-vs-c64IR at kappa" if is_complex
                          else "df64-vs-f32IR at kappa"),
           "matrix": f"poisson3d nx={nx} shifted near lambda_min"
                     + (" (unitary-rotated complex)" if is_complex else ""),
           "n": n, "kappa_target": kappa,
           "f32_ir_forward_error": e32, "df64_forward_error": edf,
           "df64_residual": rdf, "info": [i32, idf],
           "f32_seconds": round(t32, 1), "df64_seconds": round(tdf, 1),
           "backend": "cpu (1 core; timing not a perf claim)"}
    suffix = ""
    if grid is not None:
        share = -(-ludf.plan.pool_size // grid.mesh.size)
        assert share < ludf.plan.pool_size
        rec["mesh"] = f"{mesh_spec} virtual-cpu"
        rec["pool_partition"] = True
        # TWO f32 pools (hi+lo words), each sharded 1-D over the mesh
        rec["pool_entries_total_per_word"] = int(ludf.plan.pool_size)
        rec["pool_share_per_device_per_word"] = int(share)
        suffix = f"_mesh{mesh_spec}"
    if is_complex:
        suffix += "_z"
    with open(os.path.join(REPO, "docs", f"df64_scale_n{n}{suffix}.json"),
              "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    assert i32 == 0 and idf == 0
    # the experiment's claim is the RATIO (df64 recovers digits the f32
    # factors cannot) plus an absolute bound that scales with κ·2⁻⁴⁸;
    # e32's absolute level depends on how far IR stalls, so it is not
    # asserted directly
    assert edf < 1e-3 * max(e32, 1e-300), (edf, e32)
    assert edf < 100.0 * kappa * 2.0 ** -48, (edf, kappa)


if __name__ == "__main__":
    main()
