#!/usr/bin/env python
"""pdtest — the reference's sweep-test harness as a first-class runner.

Capability analog of TEST/pdtest.c + TEST/CMakeLists.txt:9-52 +
.travis_tests.sh:13-28: cross grid shapes × nrhs × Fact-reuse tiers ×
equilibration × row-perm over the reference's own fixtures (g20.rua,
big.rua, cg20.cua — read from /root/reference/EXAMPLE when present,
gallery fallbacks otherwise), check every solve against the reference's
residual test

    resid = ||b − A·x||∞ / (||A||∞ · ||x||∞ · ε · m)  <  THRESH = 20
    (TEST/pdcompute_resid.c:18, TEST/pdtest.c:40)

and print a PrintSumm-style per-driver summary (TEST/pdtest.c:84).
Writes docs/pdtest_summary.json.

Usage:
  python scripts/pdtest.py                 # full sweep + travis-15 list
  python scripts/pdtest.py --quick         # g20-only smoke sweep
  python scripts/pdtest.py --backend tpu   # run on the session backend
  python scripts/pdtest.py -f MTX --grids 1x1,2x2 --nrhs 1,3 -x 8 -m 20

Grid shapes map to virtual device meshes (the factorization runs
mesh-sharded over r×c of the backend's devices — the single-box
oversubscription strategy of the reference's CI, SURVEY.md §4); the
multi-PROCESS tier is exercised separately by tests/test_multihost.py
and examples/pddrive_grid.py.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO  # noqa: E402

sys.path.insert(0, REPO)

THRESH = 20.0                    # TEST/pdtest.c:40
_REF_EX = "/root/reference/EXAMPLE"


def _load_fixture(name):
    from superlu_dist_tpu.io import read_matrix
    from superlu_dist_tpu.models.gallery import poisson2d
    path = os.path.join(_REF_EX, name)
    if os.path.exists(path):
        return read_matrix(path).tocsr(), name
    # gallery stand-ins with the fixtures' sizes/kind
    n = {"g20.rua": 20, "big.rua": 70, "cg20.cua": 20}.get(name, 20)
    a = poisson2d(n)
    if name.endswith(".cua"):
        import superlu_dist_tpu.sparse.formats as fmts
        rng = np.random.default_rng(1)
        a = fmts.SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices,
                           a.data * np.exp(1j * rng.uniform(
                               0, 2 * np.pi, a.nnz)))
    return a, f"@poisson2d({n}){'c' if name.endswith('.cua') else ''}"


def _resid(a, x, b):
    """pdcompute_resid analog (TEST/pdcompute_resid.c:18)."""
    r = b - a.matvec(x)
    anorm = a.norm_max()
    xnorm = np.max(np.abs(x))
    eps = np.finfo(np.float64).eps
    denom = max(anorm * xnorm * eps * a.n_rows, 1e-300)
    return float(np.max(np.abs(r)) / denom)


def _one_config(a, grid, nrhs, relax, maxsuper, equil, rowperm, rows):
    """The pdtest.c inner loop: DOFACT → FACTORED → SamePattern →
    SamePattern_SameRowPerm through one configuration, each solve
    residual-checked.  Returns (nrun, nfail)."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.sparse.formats import SparseCSR
    from superlu_dist_tpu.utils.options import (Fact, Options, RowPerm)

    n = a.n_rows
    rng = np.random.default_rng(0)
    if np.issubdtype(a.data.dtype, np.complexfloating):
        xt = rng.standard_normal((n, nrhs)) + 1j * rng.standard_normal(
            (n, nrhs))
    else:
        xt = rng.standard_normal((n, nrhs))
    if nrhs == 1:
        xt = xt[:, 0]
    b = (np.stack([a.matvec(xt[:, j]) for j in range(nrhs)], axis=1)
         if nrhs > 1 else a.matvec(xt))

    base = Options(relax=relax, max_supernode=maxsuper, equil=equil,
                   row_perm=RowPerm.LargeDiag_MC64 if rowperm else
                   RowPerm.NOROWPERM)
    nrun = nfail = 0

    def check(tag, x, aa, bb):
        nonlocal nrun, nfail
        nrun += 1
        rr = (max(_resid(aa, x[:, j], bb[:, j]) for j in range(nrhs))
              if nrhs > 1 else _resid(aa, x, bb))
        ok = rr < THRESH
        if not ok:
            nfail += 1
        rows.append({"tag": tag, "resid_ratio": round(rr, 3), "pass": ok})
        return ok

    def failed(tag, info):
        """A tier that errored (info != 0) is a counted failure — it must
        reach nrun/nfail (and thus PrintSumm + the exit code), not just
        the JSON rows."""
        nonlocal nrun, nfail
        nrun += 1
        nfail += 1
        rows.append({"tag": tag, "info": int(info), "pass": False})

    # DOFACT
    x, lu, stats, info = slu.gssvx(base, a, b, grid=grid)
    if info != 0:
        failed("DOFACT", info)
        return nrun, nfail
    check("DOFACT", x, a, b)

    # FACTORED: same factors, new b
    b2 = 2.0 * b
    x, _, _, info = slu.gssvx(
        dataclasses.replace(base, fact=Fact.FACTORED), a, b2, lu=lu)
    check("FACTORED", x, a, b2) if info == 0 else failed("FACTORED", info)

    # SamePattern: new values, same pattern (fresh row perm computed)
    a2 = SparseCSR(n, n, a.indptr, a.indices, a.data * 1.5)
    x, lu2, _, info = slu.gssvx(
        dataclasses.replace(base, fact=Fact.SamePattern), a2, b, lu=lu,
        grid=grid)
    check("SamePattern", x, a2, b) if info == 0 else failed(
        "SamePattern", info)

    # SamePattern_SameRowPerm: scalings + perms + symbolic all reused
    a3 = SparseCSR(n, n, a.indptr, a.indices, a.data * 0.75)
    x, _, _, info = slu.gssvx(
        dataclasses.replace(base, fact=Fact.SamePattern_SameRowPerm),
        a3, b, lu=lu2 if lu2 is not None else lu, grid=grid)
    check("SameRowPerm", x, a3, b) if info == 0 else failed(
        "SameRowPerm", info)
    return nrun, nfail


def print_summ(typ, nfail, nrun, nerrs):
    """PrintSumm analog (TEST/pdtest.c:84)."""
    if nfail > 0:
        print(f"{typ:>3s} driver: {nfail} out of {nrun} tests failed "
              "to pass the threshold")
    else:
        print(f"All tests for {typ:>3s} driver passed the threshold "
              f"({nrun:6d} tests run)")
    if nerrs > 0:
        print(f"{nerrs:6d} error messages recorded")


def main():
    ap = argparse.ArgumentParser(
        description="pdtest-style sweep harness (TEST/pdtest.c analog)")
    ap.add_argument("-f", "--file", action="append", default=None,
                    help="matrix file(s); default: the travis fixtures")
    ap.add_argument("--grids", default="1x1,1x3,2x1,2x3",
                    help="comma list of RxC virtual grid shapes "
                         "(travis pdtest set by default)")
    ap.add_argument("--nrhs", default="1,3")
    ap.add_argument("-x", "--relax", type=int, default=8)
    ap.add_argument("-m", "--maxsuper", type=int, default=20)
    ap.add_argument("-b", "--fill", type=int, default=2,
                    help="accepted for pdtest CLI parity; fill is "
                         "estimated dynamically here")
    ap.add_argument("--quick", action="store_true",
                    help="g20-only, 1x1 + 2x2 grids")
    ap.add_argument("--travis", action="store_true",
                    help="also run the example-driver configs 9-15 of "
                         ".travis_tests.sh (pddrive1/2/3 on big.rua, "
                         "pzdrive reuse tiers on cg20.cua, ABglobal)")
    ap.add_argument("--backend", default="cpu",
                    help="cpu (default; 8 virtual devices) or the "
                         "session accelerator backend")
    ns = ap.parse_args()

    if ns.backend == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
    else:
        import jax
        jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()
    from superlu_dist_tpu.parallel.grid import gridinit
    import jax

    ndev = len(jax.devices())
    grids = []
    for spec in ns.grids.split(","):
        r, c = (int(v) for v in spec.strip().split("x"))
        if r * c <= ndev:
            grids.append((r, c))
        else:
            print(f"[pdtest] skip grid {spec}: needs {r * c} devices, "
                  f"have {ndev}")
    if ns.quick:
        grids = [(1, 1), (2, 2)] if ndev >= 4 else [(1, 1)]
    nrhss = [int(s) for s in ns.nrhs.split(",")]

    if ns.file:
        # explicit paths must exist — a typo silently swept a gallery
        # stand-in instead of the user's matrix otherwise
        missing = [p for p in ns.file if not os.path.exists(p)]
        if missing:
            ap.error(f"matrix file(s) not found: {', '.join(missing)}")
        fixtures = [(_read_path(p), p) for p in ns.file]
    else:
        names = ["g20.rua"] if ns.quick else ["g20.rua", "big.rua",
                                              "cg20.cua"]
        fixtures = [_load_fixture(n) for n in names]

    t0 = time.perf_counter()
    all_rows = []
    summary = {}
    for a, name in fixtures:
        typ = ("ZGS" if np.issubdtype(a.data.dtype, np.complexfloating)
               else "DGS")
        nrun = nfail = 0
        for (r, c) in grids:
            grid = gridinit(r, c) if r * c > 1 else None
            for nrhs in nrhss:
                for equil, rowperm in ((True, True), (False, True),
                                       (True, False)):
                    rows = []
                    n1, f1 = _one_config(a, grid, nrhs, ns.relax,
                                         ns.maxsuper, equil, rowperm,
                                         rows)
                    nrun += n1
                    nfail += f1
                    for row in rows:
                        row.update(matrix=name, grid=f"{r}x{c}",
                                   nrhs=nrhs, equil=equil,
                                   rowperm=rowperm)
                    all_rows.extend(rows)
                    mark = "ok" if f1 == 0 else f"FAIL({f1})"
                    print(f"[pdtest] {name} {r}x{c} s={nrhs} "
                          f"equil={int(equil)} rowperm={int(rowperm)} "
                          f"x={ns.relax} m={ns.maxsuper}: {n1} runs "
                          f"{mark}", flush=True)
        prev = summary.get(typ, (0, 0))
        summary[typ] = (prev[0] + nfail, prev[1] + nrun)

    examples = []
    if ns.travis:
        # .travis_tests.sh configs 9-15: the example drivers double as
        # integration tests of the Fact-reuse tiers (SURVEY.md §4)
        import subprocess
        ex_dir = os.path.join(REPO, "examples")
        big = os.path.join(_REF_EX, "big.rua")
        cua = os.path.join(_REF_EX, "cg20.cua")
        cfgs = [("pddrive1.py", big), ("pddrive2.py", big),
                ("pddrive3.py", big), ("pzdrive.py", cua),
                ("pddrive_ABglobal.py", big)]
        env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        for script, mtx in cfgs:
            args = [sys.executable, os.path.join(ex_dir, script)]
            if os.path.exists(mtx):
                args.append(mtx)
            args += ["--backend", "cpu"] if ns.backend == "cpu" else []
            t1 = time.perf_counter()
            r = subprocess.run(args, env=env, capture_output=True,
                               text=True, timeout=1200)
            ok = r.returncode == 0
            examples.append({"example": script, "matrix":
                             os.path.basename(mtx), "pass": ok,
                             "seconds": round(time.perf_counter() - t1, 1)})
            print(f"[pdtest] example {script}: "
                  f"{'ok' if ok else 'FAIL'}", flush=True)
            if not ok:
                print(r.stdout[-1500:] + r.stderr[-1500:])
                typ = "ZGS" if script.startswith("pz") else "DGS"
                f0, r0 = summary.get(typ, (0, 0))
                summary[typ] = (f0 + 1, r0 + 1)
            else:
                typ = "ZGS" if script.startswith("pz") else "DGS"
                f0, r0 = summary.get(typ, (0, 0))
                summary[typ] = (f0, r0 + 1)

    print()
    for typ, (nfail, nrun) in sorted(summary.items()):
        print_summ(typ, nfail, nrun, 0)

    out = {"thresh": THRESH, "relax": ns.relax, "maxsuper": ns.maxsuper,
           "grids": [f"{r}x{c}" for r, c in grids], "nrhs": nrhss,
           "backend": ns.backend, "seconds": round(
               time.perf_counter() - t0, 1),
           "summary": {t: {"nfail": f, "nrun": r}
                       for t, (f, r) in summary.items()},
           "examples": examples, "rows": all_rows}
    path = os.path.join(REPO, "docs", "pdtest_summary.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"\nwrote {path} ({out['seconds']}s)")
    return 1 if any(f for f, _ in summary.values()) else 0


def _read_path(p):
    from superlu_dist_tpu.io import read_matrix
    return read_matrix(p).tocsr()


if __name__ == "__main__":
    raise SystemExit(main())
