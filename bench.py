#!/usr/bin/env python
"""Benchmark: sparse LU numeric-factorization GFLOPS, TPU vs host CPU.

The metric mirrors the reference's headline number — factor Mflops printed
by PStatPrint (SRC/util.c:513-518) — on the BASELINE.md config-4 matrix
class (7-pt 3D Poisson).  The numeric factorization runs entirely on the
device via the streamed executor (numeric/stream.py).

vs_baseline is the wall-clock factorization speedup over serial SuperLU
with host CPU BLAS (scipy.sparse.linalg.splu — the same code family as the
reference) factoring the identical matrix on this machine (north-star
target: >= 4x CPU-BLAS factorization, BASELINE.json).  The reference's
distributed pdgstrf on one node is the same computation plus MPI overhead,
so serial SuperLU is the stronger (fairer) baseline.  Note the dtype
asymmetry is part of the design under measure: the TPU path factors in f32
and recovers f64 accuracy via iterative refinement (GESP + IR, SURVEY.md
§7 hard-part 1); the residual printed is AFTER refinement and must be at
reference accuracy.

Robustness (the pdtest discipline, TEST/pdtest.c — count failures, still
report): ONE JSON line always prints.  A watchdog emits whatever has been
measured if the wall budget expires (a wedged device tunnel must not
produce an empty round — round-1 lesson, VERDICT weak #1); an unreachable
accelerator triggers a CPU-backend rerun so the line still carries real
numbers, marked backend="cpu".

Prints ONE JSON line:
  {"metric": ..., "value": GFLOPS, "unit": "GFLOP/s", "vs_baseline": ...}

Env knobs: BENCH_NX (grid edge, default 48 -> n=110592; a default-config
TPU run downsizes to 16 when the compile cache is cold and the deadline
is tight — see the cold-cache guard in main), BENCH_REPS,
BENCH_DEADLINE_S (watchdog, default 1350), BENCH_PEAK_F32_TFLOPS (MFU
denominator), BENCH_NO_PROBE (skip the device-reachability probe),
BENCH_MESH (an 'RxC' mesh spec, e.g. 1x8: factor/solve run over a real
jax.Mesh through the shard_map SPMD tier and the row carries
mesh_shape/n_devices/spmd — virtual CPU devices when the backend is
cpu, so MULTICHIP rows are real measurements off-hardware too).
"""

import json
import os
import sys
import threading
import time

import numpy as np

RESULT = {"metric": "lu_factor_gflops_poisson3d", "value": None,
          "unit": "GFLOP/s", "vs_baseline": None, "phase": "startup"}
_PRINTED = threading.Lock()
_DONE = False


def _emit(final: bool):
    global _DONE
    with _PRINTED:
        if _DONE:
            return
        snap = dict(RESULT)      # snapshot: main thread mutates RESULT
        # rank-failure tolerance telemetry (parallel/recover.py): how
        # many shrink/respawn recoveries this run absorbed, and whether
        # the row's numbers rest on a recovered solve — 0/False on the
        # single-process bench unless an embedded FT driver ran
        try:
            from superlu_dist_tpu.parallel.recover import FT_EVENTS
            snap["ft_events"] = len(FT_EVENTS)
            snap["recovered"] = bool(FT_EVENTS)
        except Exception:
            snap["ft_events"] = 0
            snap["recovered"] = False
        if not final:
            snap["timeout"] = True
        print(json.dumps(snap), flush=True)
        _DONE = True             # only after a successful print


def _log(msg: str):
    print(f"[bench +{time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


T0 = time.perf_counter()
# the one import-time knob read: routed through the central registry
# (utils/options.py) like every SLU_TPU_* knob, so slulint SLU104 and the
# generated knob table cover the bench's watchdog too (bench.py sits at
# the repo root, so the package resolves from the script directory)
from superlu_dist_tpu.utils.options import env_float  # noqa: E402

DEADLINE = env_float("BENCH_DEADLINE_S")

_PHASE_T = [T0]


def _set_phase(name: str):
    """Advance RESULT["phase"], folding the previous phase's elapsed
    wall time into RESULT["phase_seconds"] — so a watchdog fire reports
    where the budget WENT, not just where the run died (the BENCH_r02
    n=110592 lesson: 'died in factor-compile' with no breakdown)."""
    now = time.perf_counter()
    prev = RESULT.get("phase")
    secs = RESULT.setdefault("phase_seconds", {})
    if prev is not None:
        secs[prev] = round(secs.get(prev, 0.0) + now - _PHASE_T[0], 3)
    RESULT["phase"] = name
    _PHASE_T[0] = now


def _watchdog():
    time.sleep(DEADLINE)
    _log(f"watchdog fired in phase '{RESULT.get('phase')}' — emitting "
         "partial result")
    try:
        # fold the in-progress phase's elapsed time in, attach the
        # compile census collected so far, and leave the flight-recorder
        # postmortem (none of this may block the JSON line)
        _set_phase(RESULT.get("phase"))
        from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
        blk = COMPILE_STATS.block(top=16)
        RESULT.setdefault("compile_seconds", blk["seconds"])
        RESULT.setdefault("compile_census", blk["census"])
        # a factor-compile death names the shape keys still UNCOMPILED
        # (announced by the executor, retired per build): the census
        # delta the next BENCH_r02-style postmortem needs to blame the
        # offending buckets instead of just counting them
        pending = COMPILE_STATS.pending()
        if pending:
            RESULT.setdefault("pending_kernels", pending)
        _aud = COMPILE_STATS.audit_block()
        if _aud["programs"]:
            RESULT.setdefault("programs_audited", _aud["programs"])
            RESULT.setdefault("donation_coverage_pct",
                              _aud["donation_coverage_pct"])
            RESULT.setdefault("baked_const_bytes",
                              _aud["baked_const_bytes"])
        if _aud["programs_sharding_audited"]:
            RESULT.setdefault("programs_sharding_audited",
                              _aud["programs_sharding_audited"])
            RESULT.setdefault("peak_bytes_est", _aud["peak_bytes_est"])
            RESULT.setdefault("replicated_bytes",
                              _aud["replicated_bytes"])
        # durable frontier FIRST (persist/checkpoint.py): flush whatever
        # the factor loop completed, record the bundle path and its
        # resume eligibility in the row — the next BENCH run of this
        # matrix resumes from it instead of recompiling/refactoring from
        # zero (the BENCH_r02 n=110592 death left nothing reusable)
        from superlu_dist_tpu.persist.checkpoint import (
            flush_active, last_checkpoint)
        ck = flush_active("bench-watchdog") or last_checkpoint()
        if ck:
            RESULT["checkpoint_path"] = ck
            try:
                from superlu_dist_tpu.persist.checkpoint import peek
                meta = peek(ck)
                RESULT["resume_eligible"] = True
                RESULT["checkpoint_groups"] = meta.get("k")
            except Exception:
                RESULT["resume_eligible"] = False
            _log(f"factor checkpoint: {ck} "
                 f"(resume_eligible={RESULT.get('resume_eligible')})")
        from superlu_dist_tpu.obs.flightrec import get_flightrec
        fr = get_flightrec()
        if fr.enabled:
            p = fr.dump("bench-watchdog",
                        detail=f"phase={RESULT.get('phase')}",
                        extra={"phase_seconds": RESULT.get("phase_seconds"),
                               "metric": RESULT.get("metric"),
                               "checkpoint": ck})
            _log(f"flight-recorder postmortem: {p}")
    except Exception as e:                          # pragma: no cover
        _log(f"watchdog telemetry failed: {type(e).__name__}: {e}")
    try:
        _emit(final=False)
    finally:
        os._exit(0)


def _probe_device(timeout_s: float = 240.0) -> bool:
    """Can the configured backend run a trivial program?  Run in a thread:
    a wedged tunnel blocks forever rather than raising (observed: remote
    worker OOM-killed mid-run leaves jax.devices() hanging)."""
    ok = []

    def run():
        try:
            import jax
            import jax.numpy as jnp
            y = (jnp.ones((128, 128)) @ jnp.ones((128, 128)))
            jax.block_until_ready(y)
            ok.append(jax.default_backend())
        except Exception as e:                      # pragma: no cover
            _log(f"device probe error: {type(e).__name__}: {e}")

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if ok:
        _log(f"device probe ok, backend={ok[0]}")
        return ok[0]
    _log("device probe FAILED (timeout or error)")
    return None


def main():
    threading.Thread(target=_watchdog, daemon=True).start()

    # BENCH_MESH=RxC: the multichip bench mode — factor/solve run over a
    # real jax.Mesh (virtual CPU devices when the backend is cpu, chips
    # on TPU) through the shard_map SPMD tier (parallel/spmd.py), and
    # the row carries mesh_shape/n_devices/spmd instead of being a
    # single-device row.  The device-count config must land BEFORE the
    # probe initializes the backend.
    MESH_SPEC = os.environ.get("BENCH_MESH", "")
    MESH_DIMS = None
    if MESH_SPEC:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        from _common import parse_mesh_spec
        MESH_DIMS = parse_mesh_spec(MESH_SPEC)
        # cpu-platform only (a TPU brings its real chips): XLA snapshots
        # XLA_FLAGS at backend init, which has not happened yet — the
        # probe below is the first jax operation
        if "host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={MESH_DIMS[2]}")

    probed = (None if os.environ.get("BENCH_NO_PROBE")
              else _probe_device())
    if os.environ.get("BENCH_REQUIRE_TPU") and not os.environ.get(
            "BENCH_NO_PROBE") and (probed is None or probed == "cpu"):
        # sweep hygiene: a tuning row measured on the CPU backend —
        # whether from a dead tunnel or a silent platform fallback — is
        # noise, not data; report and stop (the driver's official run
        # does NOT set this, so it still gets the fallback number)
        _set_phase("tpu-unreachable")
        _emit(final=True)
        return
    if not os.environ.get("BENCH_NO_PROBE") and probed is None:
        # accelerator unreachable: rerun on the CPU backend so the driver
        # still gets a real measurement (marked backend=cpu)
        _log("falling back to CPU backend in a fresh process")
        import subprocess
        # the child must finish before the PARENT watchdog fires, or its
        # real measurement is discarded — cap its budget to our remaining
        # time (never extend it)
        remaining = DEADLINE - (time.perf_counter() - T0)
        if remaining < 45:
            _log("no time left for a CPU fallback run")
            _emit(final=True)
            return
        # with a generous budget AND a warm compile cache keep the
        # driver size: the tuned CPU blocking finishes NX=48 in ~10 min
        # incl. the scipy baseline (measured 3.04x,
        # docs/bench_cpu_nx48_r4.json).  The marker mirrors the TPU
        # cold-cache guard: without it a cold fused-program compile
        # could eat the child's deadline, so shrink to NX=32 (~2 min)
        # warm markers are fingerprint-suffixed (utils/jaxcache
        # warm_marker_path): they vouch for entries in the MACHINE-SCOPED
        # cache dir, so a marker from another box/toolchain must not
        # steer this one into a cold-compile NX=48 run
        from superlu_dist_tpu.utils.jaxcache import warm_marker_path
        _cpu48 = warm_marker_path(
            "nx48_cpu", os.path.dirname(os.path.abspath(__file__)))
        cap = 48 if remaining >= 1000 and os.path.exists(_cpu48) else 32
        env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_NO_PROBE="1",
                   BENCH_DEADLINE_S=str(remaining - 30),
                   BENCH_NX=str(min(int(os.environ.get("BENCH_NX", "48")),
                                    cap)))
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, stdout=subprocess.PIPE)
        out = r.stdout.decode().strip().splitlines()
        global _DONE
        with _PRINTED:
            _DONE = True
        print(out[-1] if out else json.dumps(
            {**RESULT, "phase": "cpu-fallback-failed"}), flush=True)
        return

    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_FORCE_CPU"):
        # env JAX_PLATFORMS is overridden by the session's accelerator
        # plugin at interpreter start; only an in-process config update
        # reliably pins the CPU backend (same recipe as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()

    # flight recorder (obs/flightrec.py): the bench flies it ALWAYS ON —
    # a watchdog kill or mid-factor breakdown must leave a postmortem
    # (last events, phase stack, compile census) instead of nothing (the
    # BENCH_r02 outcome).  SLU_TPU_FLIGHTREC overrides the dump path;
    # installed BEFORE the first get_tracer() so the tracer composition
    # feeds the ring from every existing instrumentation site.
    from superlu_dist_tpu.obs import flightrec
    fr = flightrec.get_flightrec()
    if not fr.enabled:
        fr = flightrec.FlightRecorder(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".cache",
            "bench_flightrec_%p.json"))
        flightrec.install(fr, arm_signals=True)
    RESULT["flightrec"] = fr.dump_path

    # structured tracing (obs/trace.py): SLU_TPU_TRACE=<path> turns this
    # run into one self-describing artifact — phase spans from this
    # function, dispatch/kernel-shape spans from the executors, comm
    # spans for the host<->device transfers (docs/OBSERVABILITY.md)
    from superlu_dist_tpu.obs.trace import get_tracer
    tracer = get_tracer()
    if tracer.enabled and tracer.path:
        RESULT["trace"] = tracer.path

    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.numeric.factor import NumericFactorization
    from superlu_dist_tpu.drivers.gssvx import LUFactorization
    from superlu_dist_tpu.refine.ir import iterative_refinement

    NX = int(os.environ.get("BENCH_NX", "48"))   # n = NX^3 = 110,592:
    # Cold-cache guard: compiling the default NX=48 kernel set through
    # the remote tunnel takes ~20-40 min — far past the default watchdog
    # — and a watchdog kill mid-compile both yields a null row AND wedges
    # the relay (the r2/r3 outage trigger).  .hw_done/nx48_default marks
    # the default set warm in .cache/jax (written by
    # scripts/hw_session_r3.sh AND by this script itself after a
    # successful default-config warm); without it, a DEFAULT-config TPU
    # run inside a tight deadline drops to NX=16, whose 14 kernels
    # compile in ~2 min — a real measured number instead of a timeout.
    # Any kernel-set-affecting env knob means a deliberate sweep run
    # with its own deadline discipline: the guard stays out of the way.
    _KNOBS = ("BENCH_NX", "BENCH_DTYPE", "BENCH_GRANULARITY",
              "BENCH_MAXSUPER", "BENCH_RELAX", "BENCH_MINBUCKET",
              "BENCH_GROWTH", "BENCH_AMALG", "BENCH_MATRIX",
              "SLU_TPU_PRECISION", "SLU_TPU_GEMM_PREC", "SLU_TPU_PALLAS",
              "SLU_TPU_PIVOT_KERNEL",
              "SLU_TPU_HOST_FLOPS", "SLU_TPU_DIAG_INV",
              "SLU_TPU_SCHEDULE", "SLU_TPU_SCHED_WINDOW",
              "SLU_TPU_SCHED_ALIGN", "SLU_TPU_BUCKET_BASE",
              "SLU_TPU_BUCKET_GROWTH", "SLU_TPU_BUCKET_CLOSED",
              "SLU_TPU_BUCKET_KEYS", "SLU_TPU_EXECUTOR",
              # mesh mode compiles a different program set entirely
              "BENCH_MESH", "SLU_TPU_SPMD",
              # solve-kernel-set knobs (solve/plan.py): a set one means
              # a deliberate solve sweep with its own deadline discipline
              "BENCH_SOLVE_NRHS", "SLU_TPU_SOLVE_SCHEDULE",
              "SLU_TPU_SOLVE_WINDOW", "SLU_TPU_SOLVE_ALIGN",
              "SLU_TPU_SOLVE_TRSM_LEAF", "SLU_TPU_SOLVE_NRHS_MAX",
              "SLU_TPU_SOLVE_NRHS_GROWTH")
    # BENCH_NX=48 is exactly the default size, so an explicit "48" (the
    # hardware session's nx48_default config) still counts as the default
    # kernel set — its successful run must warm the default marker
    _knob_set = {k for k in _KNOBS if k in os.environ}
    if os.environ.get("BENCH_NX") == "48":
        _knob_set.discard("BENCH_NX")
    _default_cfg = not _knob_set
    # fingerprint-suffixed (see the CPU-fallback marker above): the
    # warmth claim is per machine-scoped cache dir
    from superlu_dist_tpu.utils.jaxcache import warm_marker_path
    _marker = warm_marker_path(
        "nx48_default", os.path.dirname(os.path.abspath(__file__)))
    if (_default_cfg and jax.default_backend() != "cpu"
            and DEADLINE - (time.perf_counter() - T0) < 2400
            and not os.path.exists(_marker)):
        _log("cold compile cache + tight deadline: dropping to NX=16 "
             "(guaranteed-compile size) — run scripts/hw_session_r3.sh "
             "to warm the NX=48 set")
        RESULT["downsized_from_nx"] = NX
        NX = 16
    # large enough that the big separator fronts drive the MXU (the r1
    # bench at NX=24 was latency-bound, VERDICT weak #3); with compact
    # (lpanel, upanel) factor storage the whole factorization fits
    # single-chip HBM (~8 GB at NX=48 vs 16 GB on v5e)
    REPS = int(os.environ.get("BENCH_REPS", "3"))
    # bfloat16 engages the MXU's native-rate passes (~4x the f32-HIGHEST
    # rate); IR still recovers f64 residuals on well-conditioned systems
    # (more steps).  f32 is the safe default.
    DTYPE = os.environ.get("BENCH_DTYPE", "float32")
    # MFU denominator (utils/peaks.py): per-backend/per-GEMM-tier peak —
    # TPU kinds tabulated, CPU calibrated with a micro-GEMM — so a CPU
    # row never divides by a TPU constant and prints mfu_pct 0.0 (the
    # historical honesty bug).  SLU_TPU_PEAK_GFLOPS overrides; the
    # legacy BENCH_PEAK_F32_TFLOPS knob still wins when explicitly set.
    from superlu_dist_tpu.ops.dense import gemm_precision
    from superlu_dist_tpu.utils.peaks import detect_peak_gflops
    GEMM_PREC = gemm_precision(None)
    RESULT["gemm_precision"] = GEMM_PREC
    _legacy_peak = env_float("BENCH_PEAK_F32_TFLOPS", default=0.0)
    if _legacy_peak > 0:
        PEAK_GF, PEAK_SRC = _legacy_peak * 1e3, "env:BENCH_PEAK_F32_TFLOPS"
    else:
        PEAK_GF, PEAK_SRC = detect_peak_gflops(GEMM_PREC)
    RESULT["peak_gflops"] = round(PEAK_GF, 1)
    RESULT["peak_source"] = PEAK_SRC
    # Blocking defaults are backend-specific.  TPU: wide supernodes feed
    # the MXU (SURVEY.md §7 step 10 — the reference's NSUP=128 is
    # CPU-cache-sized) and keep the streamed executor's kernel count
    # small.  CPU fallback: no MXU to feed, so minimize PADDING instead —
    # tighter buckets/amalgamation cut executed/structural flops from
    # 1.37x to 1.09x and put the fused executor at 1.18x scipy splu at
    # NX=32 (the r4 CPU sweep; r3's group-streamed CPU row lost at
    # 0.66x).  Env-overridable for on-hardware tuning sweeps.
    _cpu = jax.default_backend() == "cpu"
    RELAX = int(os.environ.get("BENCH_RELAX", "128" if _cpu else "256"))
    MAX_SUPER = int(os.environ.get("BENCH_MAXSUPER",
                                   "256" if _cpu else "1024"))
    MIN_BUCKET = int(os.environ.get("BENCH_MINBUCKET",
                                    "16" if _cpu else "32"))
    GROWTH = float(os.environ.get("BENCH_GROWTH", "1.05" if _cpu else "1.3"))
    # fill-tolerant amalgamation (symbfact.amalgamate_supernodes) is the
    # round-3 MFU lever: at NX=48 it cuts 10707 supernodes/325 levels/119
    # kernels to 587/13/~45 and the executed-over-structural flop ratio
    # from 15.7x to ~1.7x
    AMALG = float(os.environ.get("BENCH_AMALG", "1.05" if _cpu else "1.2"))
    RESULT["blocking"] = [RELAX, MAX_SUPER, MIN_BUCKET, GROWTH, AMALG]

    backend = jax.default_backend()
    RESULT["backend"] = backend
    # cache_isa_mismatch: enable_compile_cache above verified the cache
    # dir's host-feature stamp — nonzero means a foreign-entry class the
    # fingerprint failed to scope out (the BENCH_r05 'machine features
    # don't match ... SIGILL' tail); the gate asserts it stays 0
    from superlu_dist_tpu.utils.jaxcache import isa_mismatch_count
    RESULT["cache_isa_mismatch"] = isa_mismatch_count()
    MESH = None
    if MESH_DIMS:
        from superlu_dist_tpu.parallel.grid import gridinit
        MESH = gridinit(MESH_DIMS[0], MESH_DIMS[1]).mesh
        RESULT["mesh_shape"] = [MESH_DIMS[0], MESH_DIMS[1]]
        RESULT["n_devices"] = MESH_DIMS[2]
        _log(f"mesh mode: {MESH_DIMS[0]}x{MESH_DIMS[1]} "
             f"({MESH_DIMS[2]} {backend} devices)")
    if os.environ.get("BENCH_REQUIRE_TPU") and backend == "cpu":
        # closes the BENCH_NO_PROBE hole: with the probe skipped the
        # earlier require-check can't fire, so verify the resolved
        # backend itself — a TPU-only sweep must never record a CPU row
        _set_phase("tpu-unreachable")
        _log("BENCH_REQUIRE_TPU set but the backend resolved to cpu — "
             "refusing to record a CPU row")
        _emit(final=True)
        return
    _set_phase("prepare")
    t_phase = time.perf_counter()

    # BENCH_MATRIX=geo3d swaps in the irregular FEM-like family
    # (random_geometric_3d, the audikw_1-class surrogate — BASELINE
    # config 5) at the same n = NX^3, guarding blocking choices against
    # overfitting to the regular Poisson stencil
    MATRIX = os.environ.get("BENCH_MATRIX", "poisson3d")
    if MATRIX not in ("poisson3d", "geo3d"):
        raise SystemExit(f"BENCH_MATRIX={MATRIX!r}: expected poisson3d|geo3d")
    if MATRIX == "geo3d":
        from superlu_dist_tpu.models.gallery import random_geometric_3d
        a = random_geometric_3d(NX ** 3)
    else:
        a = poisson3d(NX)
    opts = Options()
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(opts, a, sym)
    sf = symbolic_factorize(sym, col_order, relax=RELAX,
                            max_supernode=MAX_SUPER, amalg_tol=AMALG)
    # executor granularity resolved BEFORE the plan: the mega executor
    # wants the shape-key set CLOSED at plan build (numeric/plan.py —
    # the O(1)-compiled-programs contract), which an explicit
    # SLU_TPU_BUCKET_CLOSED setting can still override either way
    gran = os.environ.get(
        "BENCH_GRANULARITY",
        ("auto" if MESH is not None            # -> spmd via get_executor
         else "fused" if backend == "cpu" else "group"))
    _closed = (True if gran == "mega"
               and "SLU_TPU_BUCKET_CLOSED" not in os.environ else None)
    plan = build_plan(sf, min_bucket=MIN_BUCKET, growth=GROWTH,
                      closed=_closed)
    RESULT["bucket_set_digest"] = plan.bucket_set_digest()
    RESULT["bucket_closed"] = plan.closed
    if plan.pool_size >= 2 ** 31 and not jax.config.jax_enable_x64:
        # beyond-int32 pool (n>=~600k at f32): indices must stay int64
        # (the reference's XSDK_INDEX_SIZE=64 tier); costs some index
        # bandwidth on device, required for correctness
        _log(f"pool_size {plan.pool_size:.3g} >= 2^31 — enabling x64 "
             "index mode")
        jax.config.update("jax_enable_x64", True)
    # numpy has no bf16, so that case stages through f32; every other
    # dtype keeps full precision.  The executor casts to DTYPE on upload;
    # the GESP threshold uses DTYPE's own epsilon.
    host_dt = np.float32 if DTYPE == "bfloat16" else np.dtype(DTYPE)
    avals_np = sym.data[sf.value_perm].astype(host_dt)
    eps = float(jnp.finfo(jnp.dtype(DTYPE)).eps)
    thresh_np = np.asarray(np.sqrt(eps) * a.norm_max(), host_dt)
    n = a.n_rows
    RESULT["metric"] = f"lu_factor_gflops_{MATRIX}_n{n}_{DTYPE}"
    RESULT["flops"] = plan.flops
    # dispatch-schedule telemetry (numeric/plan.py): scheduler name,
    # group count before/after dataflow aggregation, mean fronts per
    # dispatch and the dependent-group critical path
    sched = plan.schedule_stats(itemsize=host_dt.itemsize)
    RESULT["schedule"] = sched["schedule"]
    RESULT["n_groups"] = sched["n_groups"]
    RESULT["n_level_groups"] = sched["n_level_groups"]
    RESULT["occupancy"] = sched["occupancy"]
    RESULT["critical_path"] = sched["critical_path"]
    # irregular gather/scatter traffic (the number the Pallas fused
    # path exists to shrink — data-movement honesty next to the flop
    # padding factor)
    RESULT["bytes_moved"] = sched["bytes_moved"]
    _log(f"prepared n={n} schedule={sched['schedule']} "
         f"groups={sched['n_groups']} (level {sched['n_level_groups']}) "
         f"occupancy={sched['occupancy']} flops={plan.flops / 1e9:.0f} GF")

    tracer.complete("prepare", "phase", t_phase,
                    time.perf_counter() - t_phase, n=n,
                    groups=len(plan.groups))
    _set_phase("factor-compile")
    t_phase = time.perf_counter()
    # compile census window (obs/compilestats.py): everything the warm
    # call below builds lands in compile_seconds + the per-bucket census
    # — the ROADMAP item 3 acceptance fields
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    _comp0 = COMPILE_STATS.marker()
    # BENCH_GRANULARITY: "group" (one kernel per shape key, streamed),
    # "level" (one program per elimination level), "mega" (ONE
    # data-driven program per closed shape bucket, numeric/mega.py —
    # the O(1)-compiled-programs executor for the TPU compile wall), or
    # "fused" (the WHOLE factorization as one XLA program — viable
    # again now that amalgamation leaves ~45 groups; zero dispatch
    # overhead, XLA schedules across groups).  Default follows
    # get_executor's "auto" rule (numeric/factor.py): fused on CPU —
    # per-group streaming there spent 56% of factor time in Python
    # dispatch (BENCH_r03, 0.66x scipy) while compile is cheap; group
    # on accelerators, where per-kernel compile through the tunnel
    # dominates instead.  (gran itself is resolved above, pre-plan.)
    if MESH is not None:
        # mesh mode routes through the central dispatch so the auto rule
        # (numeric/factor.py) picks the shard_map SPMD tier on a
        # single-process mesh; BENCH_GRANULARITY still names an explicit
        # tier (spmd|stream|mega|fused — "group"/"level" mean stream)
        from superlu_dist_tpu.numeric.factor import get_executor
        ex = get_executor(plan, DTYPE,
                          executor={"group": "stream",
                                    "level": "stream"}.get(gran, gran),
                          mesh=MESH, gemm_prec=GEMM_PREC)
        # spmd: did the row actually run the one-program shard_map tier
        # (granularity "program"), or a GSPMD streamed/mega fallback?
        RESULT["spmd"] = ex.granularity == "program"
        _log(f"mesh executor: {type(ex).__name__} "
             f"(granularity={ex.granularity}, spmd={RESULT['spmd']})")
    elif gran == "mega":
        from superlu_dist_tpu.numeric.mega import MegaExecutor
        ex = MegaExecutor(plan, DTYPE)
    elif gran == "fused":
        from superlu_dist_tpu.numeric.factor import make_factor_fn

        class _Fused:
            offload = "none"
            granularity = "fused"
            n_kernels = 1
            last_profile = None
            last_dispatch_seconds = None

            def __init__(self):
                from superlu_dist_tpu.symbolic.symbfact import _front_flops
                self._fn = make_factor_fn(plan, DTYPE)
                # the fused path keeps real batch sizes (no pow-2 pad)
                self.executed_flops = float(sum(
                    g.batch * _front_flops(g.w, g.u) for g in plan.groups))

            def __call__(self, avals, thresh):
                return self._fn(avals, thresh)

        ex = _Fused()
    else:
        ex = StreamExecutor(plan, DTYPE, granularity=gran)
    # Crash-consistent warm call (persist/checkpoint.py): checkpoint the
    # compile/warm factorization — the phase the BENCH_r02 n=110592 run
    # died in — so a watchdog kill leaves a durable frontier in the row,
    # and a prior killed run's frontier (plan-fingerprint + value-digest
    # verified) is RESUMED instead of refactoring from zero.  The timed
    # reps below run with checkpointing disarmed: the interval flush
    # blocks the async dispatch stream and would poison the measurement.
    _ckpt = None
    if gran in ("group", "mega") and DTYPE != "bfloat16":
        try:
            from superlu_dist_tpu.persist.checkpoint import (
                FactorCheckpointer, load_checkpoint)
            from superlu_dist_tpu.utils.options import env_int
            _ck_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), ".cache",
                "bench_ckpt", RESULT["metric"])
            try:
                st = load_checkpoint(_ck_dir, plan=plan,
                                     pattern_values=avals_np,
                                     thresh=thresh_np, dtype=DTYPE,
                                     gemm_prec=GEMM_PREC)
                ex.resume = st
                RESULT["resumed_from_groups"] = st.k
                _log(f"resuming factorization from checkpoint frontier "
                     f"{st.k}/{len(plan.groups)} ({_ck_dir})")
            except Exception:
                pass            # no / incompatible checkpoint: fresh run
            _ckpt = FactorCheckpointer(
                _ck_dir, plan, avals_np, thresh_np, DTYPE,
                every=env_int("SLU_TPU_CKPT_EVERY") or 8,
                gemm_prec=GEMM_PREC)
            ex.checkpoint = _ckpt
        except Exception as e:                      # pragma: no cover
            _log(f"checkpoint arming failed: {type(e).__name__}: {e}")
            _ckpt = None
    RESULT["offload"] = ex.offload
    RESULT["granularity"] = ex.granularity
    RESULT["n_kernels"] = ex.n_kernels
    RESULT["executed_flops"] = ex.executed_flops
    RESULT["padding_factor"] = round(ex.executed_flops / plan.flops, 2)
    t_up = time.perf_counter()
    avals = jnp.asarray(avals_np)
    thresh = jnp.asarray(thresh_np)
    if tracer.enabled:
        jax.block_until_ready((avals, thresh))
        tracer.complete("upload-avals", "comm", t_up,
                        time.perf_counter() - t_up, op="h2d",
                        bytes=int(avals_np.nbytes + thresh_np.nbytes))
    out = ex(avals, thresh)
    jax.block_until_ready(out[0])
    _blk = COMPILE_STATS.block(since=_comp0, top=16)
    RESULT["compile_seconds"] = _blk["seconds"]
    RESULT["compile_census"] = _blk["census"]
    RESULT["compile_persistent_hits"] = _blk["persistent_hits"]
    # programs actually built this run (vs n_kernels = the full set)
    RESULT["n_kernels_compiled"] = _blk["builds"]
    # time spent on builds the persistent cache did NOT serve from disk
    # — exactly 0 on a bucket-set warm start (the acceptance field; the
    # plain compile_seconds keeps trace/lower/cache-load overhead)
    RESULT["compile_fresh_seconds"] = _blk["fresh_seconds"]
    # the mega executor AOT-stages, so the exact XLA-compile stage (the
    # part the persistent cache eliminates) is known separately
    _xla = sum(r.compile_seconds or 0.0
               for r in COMPILE_STATS.records[_comp0:])
    if _xla:
        RESULT["xla_compile_seconds"] = round(_xla, 4)
    # program-audit fields (SLU_TPU_VERIFY_PROGRAMS=1, slulint v4): how
    # much of the executors' declared-dead input volume is donated and
    # how many bytes the compiled programs bake as constants — the
    # peak-memory and warm-start honesty axes of the IR-audit tier
    _aud = COMPILE_STATS.audit_block()
    if _aud["programs"]:
        RESULT["programs_audited"] = _aud["programs"]
        RESULT["donation_coverage_pct"] = _aud["donation_coverage_pct"]
        RESULT["baked_const_bytes"] = _aud["baked_const_bytes"]
    # sharding-audit fields (SLU_TPU_VERIFY_SHARDING=1, slulint v6):
    # the worst program's static peak-live-bytes estimate and the
    # gathered/replicated traffic the SLU119 walk priced — the
    # will-it-fit-HBM axes of the sharding tier
    if _aud["programs_sharding_audited"]:
        RESULT["programs_sharding_audited"] = \
            _aud["programs_sharding_audited"]
        RESULT["peak_bytes_est"] = _aud["peak_bytes_est"]
        RESULT["replicated_bytes"] = _aud["replicated_bytes"]
    tracer.complete("factor-compile", "phase", t_phase,
                    time.perf_counter() - t_phase,
                    kernels=ex.n_kernels, offload=ex.offload,
                    compile_seconds=_blk["seconds"])
    _log(f"warm (compile) done, kernels={ex.n_kernels}, "
         f"offload={ex.offload}, compile {_blk['seconds']:.1f}s "
         f"({_blk['builds']} builds, {_blk['persistent_hits']} disk hits)")
    if _ckpt is not None:
        # the warm factorization completed: the frontier is no longer
        # needed (and must not leak into the timed reps)
        ex.checkpoint = None
        _ckpt.complete(cleanup=True)
        _ckpt = None
    if _default_cfg and NX == 48 and backend != "cpu":
        # default NX=48 set is now in .cache/jax: future default runs
        # need not downsize (self-healing, same marker the hardware
        # session writes)
        os.makedirs(os.path.dirname(_marker), exist_ok=True)
        open(_marker, "a").close()
    if _default_cfg and NX == 48 and backend == "cpu" and gran == "fused":
        # the NX=48 CPU fused program (default blocking knobs — a custom
        # BENCH_RELAX/AMALG program would not warm the default kernels)
        # is cached: the CPU fallback may keep the driver size from now
        # on (see the fallback cap)
        from superlu_dist_tpu.utils.jaxcache import warm_marker_path
        mk = warm_marker_path(
            "nx48_cpu", os.path.dirname(os.path.abspath(__file__)))
        os.makedirs(os.path.dirname(mk), exist_ok=True)
        open(mk, "a").close()

    _set_phase("factor-time")
    times = []
    mfu_reps = []
    for rep in range(REPS):
        t0 = time.perf_counter()
        out = ex(avals, thresh)
        jax.block_until_ready(out[0])
        dt = time.perf_counter() - t0
        tracer.complete("FACT", "phase", t0, dt, rep=rep)
        times.append(dt)
        # progressive: every rep updates the reported number, so a
        # watchdog fire mid-loop still carries a real measurement; mfu
        # is recorded PER REP (and rounded to 4 decimals — small-but-
        # real CPU utilizations must not print as 0.0) so the perf-
        # regress gate sees precision-tagged per-rep baselines
        mfu_reps.append(round(100.0 * plan.flops / dt / (PEAK_GF * 1e9),
                              4))
        t_dev = min(times)
        RESULT["value"] = round(plan.flops / t_dev / 1e9, 2)
        RESULT["factor_seconds"] = t_dev
        RESULT["mfu_pct"] = round(
            100.0 * plan.flops / t_dev / (PEAK_GF * 1e9), 4)
        RESULT["mfu_pct_reps"] = list(mfu_reps)
        if ex.last_dispatch_seconds is not None:
            RESULT["dispatch_seconds"] = round(ex.last_dispatch_seconds, 4)
        if getattr(ex, "last_offload_wait_seconds", None) is not None:
            RESULT["offload_wait_seconds"] = round(
                ex.last_offload_wait_seconds, 4)
        _log(f"rep {rep}: {dt:.3f}s -> "
             f"{plan.flops / dt / 1e9:.1f} GFLOP/s")
    fronts, tiny = out
    RESULT["tiny_pivots"] = int(tiny)
    # legacy stderr kernel lines only under the (deprecated)
    # SLU_TPU_PROFILE knob — the tracer's structured kernel spans are the
    # first-class record (last_profile also fills whenever tracing is on)
    from superlu_dist_tpu.utils.options import deprecated_knob_warning
    deprecated_knob_warning(
        "SLU_TPU_PROFILE",
        "set SLU_TPU_TRACE=trace.json instead — the tracer's kernel "
        "spans are the structured record of the same timings")
    if ex.last_profile and os.environ.get("SLU_TPU_PROFILE"):
        # kernel-shape trace (dgemm_mnk.dat analog) to stderr, top by time
        top = sorted(ex.last_profile, key=lambda r: -r["seconds"])[:15]
        for r in top:
            print(f"# lvl={r['level']:<3d} B={r['batch']:<5d} "
                  f"m={r['m']:<5d} w={r['w']:<5d} u={r['u']:<5d} "
                  f"{r['seconds'] * 1e3:8.2f} ms "
                  f"{r['gflop'] / max(r['seconds'], 1e-12):8.1f} GF/s",
                  file=sys.stderr)

    # Everything past this point (solve, residual, CPU baseline) must not
    # be able to zero the factor GFLOPS: each phase degrades independently
    # and the JSON line always prints.
    _set_phase("solve-residual")
    t_phase = time.perf_counter()
    try:
        numeric = NumericFactorization(plan=plan, fronts=list(fronts),
                                       tiny_pivots=int(tiny),
                                       dtype=jnp.dtype(DTYPE))
        ones = np.ones(n)
        ident = np.arange(n, dtype=np.int64)
        lu = LUFactorization(n=n, options=Options(), equed="N", dr=ones,
                             dc=ones, r1=ones, c1=ones, row_order=ident,
                             col_order=None, sf=sf, plan=plan,
                             numeric=numeric, a=a, mesh=MESH)
        xt = np.random.default_rng(0).standard_normal(n)
        b = a.matvec(xt)
        x, _ = iterative_refinement(a, b, lu.solve_factored(b),
                                    lu.solve_factored)
        RESULT["residual"] = float(np.linalg.norm(b - a.matvec(x))
                                   / max(np.linalg.norm(b), 1e-300))
        # ||x - xtrue||_inf / ||x||_inf — the pdinf_norm_error metric
        # (EXAMPLE/pddrive.c:235)
        RESULT["xtrue_inf_error"] = float(
            np.max(np.abs(x - xt)) / max(np.max(np.abs(x)), 1e-300))
        # warm solve timing + rate — the reference's solve Mflops line
        # (SRC/util.c:521-529); flops ~ 2*(nnz(L)+nnz(U)) per RHS
        t0 = time.perf_counter()
        lu.solve_factored(b)
        RESULT["solve_seconds"] = round(time.perf_counter() - t0, 5)
        RESULT["solve_gflops"] = round(
            2.0 * (sf.nnz_L + sf.nnz_U)
            / max(RESULT["solve_seconds"], 1e-12) / 1e9, 3)
        solve_path = ("device" if lu.solve_path == "auto"
                      and backend != "cpu" and not numeric.on_host
                      else "host")
        if lu.solve_path == "host" and backend != "cpu":
            solve_path = "host-fallback"
        if MESH is not None and lu.dev_solver is not None:
            from superlu_dist_tpu.parallel.spmd import SpmdSolver
            if isinstance(lu.dev_solver, SpmdSolver):
                # the mesh row's triangular sweeps ran as shard_map
                # programs (one per sweep bucket), not the host loop
                solve_path = "device-spmd"
        RESULT["solve_path"] = solve_path
        _log(f"residual {RESULT['residual']:.2e} via {solve_path} solve")
    except Exception as e:                       # pragma: no cover
        RESULT["solve_path"] = f"failed: {type(e).__name__}: {e}"
        _log(f"solve phase failed: {e}")

    tracer.complete("solve-residual", "phase", t_phase,
                    time.perf_counter() - t_phase)

    # Serving hot path (ROADMAP item 1): the DEVICE batched solve at a
    # many-RHS sweep — solve_gflops becomes {"1": ..., "64": ...,
    # "1024": ...} (structural flops, honest numerator) plus the
    # solve-plan schedule stats and the nrhs-inclusive padding factor
    # (solve/plan.py).  Each size degrades independently under the
    # remaining watchdog budget; a failure leaves the scalar host
    # numbers from the phase above in place.
    _set_phase("solve-bench")
    t_phase = time.perf_counter()
    try:
        _sizes = [int(s) for s in os.environ.get(
            "BENCH_SOLVE_NRHS", "1,64,1024").split(",") if s.strip()]
        if numeric.on_host:
            # offloaded factors would re-upload per solve — the device
            # solve bench would measure the PCIe link, not the sweeps
            RESULT["solve_bench"] = "skipped: factors host-resident"
        elif _sizes:
            from superlu_dist_tpu.solve.plan import build_solve_plan
            lu.solve_path = "device"
            lu.dev_solver = None
            sp = build_solve_plan(plan)
            RESULT["solve_plan"] = sp.schedule_stats(nrhs=max(_sizes))
            from superlu_dist_tpu.obs.slo import get_accounter
            acct = get_accounter()
            gfl = {}
            secs = {}
            lat50 = {}
            lat99 = {}
            rng = np.random.default_rng(1)
            sflops = 2.0 * (sf.nnz_L + sf.nnz_U)
            for k in _sizes:
                if DEADLINE - (time.perf_counter() - T0) < 180:
                    _log(f"solve-bench: budget low, skipping nrhs={k}+")
                    break
                d = rng.standard_normal((n, k))
                d = d[:, 0] if k == 1 else d
                lu.solve_factored(d)          # warm (compile) call
                # repeated timed solves: min feeds the throughput
                # number (the factor-rep convention), the distribution
                # feeds the latency percentiles the SLO layer and
                # bench_history track
                reps = []
                for _ in range(8):
                    t0 = time.perf_counter()
                    lu.solve_factored(d)
                    reps.append(time.perf_counter() - t0)
                    acct.observe(k, reps[-1], klass="bench")
                    if DEADLINE - (time.perf_counter() - T0) < 150:
                        break
                dt = min(reps)
                reps_ms = np.asarray(reps) * 1e3
                secs[str(k)] = round(dt, 5)
                gfl[str(k)] = round(sflops * k / max(dt, 1e-12) / 1e9, 3)
                lat50[str(k)] = round(float(np.percentile(reps_ms, 50)), 4)
                lat99[str(k)] = round(float(np.percentile(reps_ms, 99)), 4)
                _log(f"solve nrhs={k}: {dt:.4f}s -> "
                     f"{gfl[str(k)]} GFLOP/s (device), "
                     f"p50 {lat50[str(k)]} ms over {len(reps)} reps")
                # progressive, like the factor reps: a watchdog fire
                # mid-sweep still carries the sizes measured so far
                RESULT["solve_gflops"] = dict(gfl)
                RESULT["solve_seconds_nrhs"] = dict(secs)
                RESULT["latency_p50_ms"] = dict(lat50)
                RESULT["latency_p99_ms"] = dict(lat99)
                RESULT["solve_path"] = "device"
                if MESH is not None and lu.dev_solver is not None:
                    from superlu_dist_tpu.parallel.spmd import SpmdSolver
                    if isinstance(lu.dev_solver, SpmdSolver):
                        RESULT["solve_path"] = "device-spmd"
                if lu.dev_solver is not None \
                        and lu.dev_solver.last_solve_stats:
                    RESULT["solve_padding_factor"] = \
                        lu.dev_solver.last_solve_stats["padding_factor"]
            if lu.solve_path != "device":
                # the auto-fallback fired mid-bench: record why
                RESULT["solve_path"] = "host-fallback"
                RESULT["solve_fallback"] = lu.solve_fallback_reason
    except Exception as e:                       # pragma: no cover
        RESULT["solve_bench"] = f"failed: {type(e).__name__}: {e}"
        _log(f"solve-bench phase failed: {e}")

    tracer.complete("solve-bench", "phase", t_phase,
                    time.perf_counter() - t_phase)

    # Baseline: serial SuperLU (same code family as the reference) with
    # host CPU BLAS, factoring the identical matrix
    _set_phase("cpu-baseline")
    t_phase = time.perf_counter()
    try:
        import scipy.sparse as sp
        from scipy.sparse.linalg import splu
        A = sp.csr_matrix((a.data, a.indices, a.indptr),
                          shape=(n, n)).tocsc()
        t0 = time.perf_counter()
        splu(A)
        t_cpu = time.perf_counter() - t0
        RESULT["baseline_seconds"] = t_cpu
        RESULT["baseline"] = ("scipy.splu (serial SuperLU, f64, host BLAS),"
                              " same matrix")
        RESULT["vs_baseline"] = round(t_cpu / RESULT["factor_seconds"], 2)
        _log(f"scipy splu baseline {t_cpu:.2f}s -> "
             f"vs_baseline {RESULT['vs_baseline']}x")
    except Exception as e:                        # pragma: no cover
        _log(f"baseline failed: {e}")

    tracer.complete("cpu-baseline", "phase", t_phase,
                    time.perf_counter() - t_phase)
    _set_phase("done")
    # flush explicitly: the watchdog's os._exit skips atexit, so the
    # artifact must be on disk before the final line prints
    tracer.close()
    _emit(final=True)


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:           # the ONE-JSON-line contract holds
        RESULT.setdefault("error", f"{type(e).__name__}: {e}")
        _emit(final=True)
        raise
