"""Phase timing / flop statistics.

Analog of ``SuperLUStat_t`` (SRC/util_dist.h:83-96) with the per-phase
``utime[]``/``ops[]`` arrays over the PhaseType enum
(SRC/superlu_enum_consts.h:65-89), and of ``PStatPrint`` (SRC/util.c:484-534)
which reports phase seconds plus factor/solve Mflops — the baseline metric
source (BASELINE.md).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

#: Phases, mirroring the reference's PhaseType (superlu_enum_consts.h:65-89).
PHASES = (
    "EQUIL", "ROWPERM", "COLPERM", "ETREE", "SYMBFACT", "DIST",
    "FACT", "SOLVE", "REFINE",
)


@dataclass
class RungRecord:
    """One escalation-ladder action (drivers/gssvx.py): what was tried,
    why, and what it bought.  berr values are max-over-RHS componentwise
    backward errors before/after the rung."""

    name: str                     # "residual-precision" | "hiprec-factors"
                                  # | "refactor-rescale"
    detail: str = ""              # e.g. the dtype escalated to
    berr_before: float = float("inf")
    berr_after: float = float("inf")
    seconds: float = 0.0


@dataclass
class SolveReport:
    """What the solve did to earn trust — the rcond/ferr/berr outputs of
    the reference driver (pdgssvx.c's pdgscon + pdgsrfs reporting) plus
    the recovery ladder's actions.  Attached to Stats.solve_report by
    drivers/gssvx.gssvx; callers inspect it to see *what* degraded and
    *why* the answer is still trustworthy."""

    rcond: float | None = None    # Hager–Higham 1-norm estimate (pdgscon)
    ferr: list | None = None      # per-RHS normwise forward-error bounds
    berr: float | None = None     # final max-over-RHS backward error
    berr_history: list = field(default_factory=list)
    rungs: list = field(default_factory=list)     # RungRecord per escalation
    tiny_pivots: int = 0          # ReplaceTinyPivot count for THIS solve
    refine_steps: int = 0
    target: float | None = None   # the berr convergence target applied
    converged: bool = True        # final berr <= target (True w/o refine)
    finite: bool = True           # solution passed the isfinite sentinel
    factor_dtype: str = ""        # dtype of the factors the answer rests on

    def summary(self) -> str:
        parts = [f"factor dtype {self.factor_dtype}" if self.factor_dtype
                 else ""]
        if self.rcond is not None:
            parts.append(f"rcond {self.rcond:.3e}")
        if self.berr is not None:
            parts.append(f"berr {self.berr:.3e}")
        if self.ferr:
            parts.append(f"ferr {max(self.ferr):.3e}")
        if self.tiny_pivots:
            parts.append(f"{self.tiny_pivots} tiny pivots replaced")
        for r in self.rungs:
            parts.append(f"rung {r.name}[{r.detail}] "
                         f"berr {r.berr_before:.2e}->{r.berr_after:.2e}")
        if not self.finite:
            parts.append("NON-FINITE")
        if not self.converged:
            parts.append("NOT CONVERGED to target")
        return "; ".join(p for p in parts if p)


@dataclass
class Stats:
    utime: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    ops: dict = field(default_factory=lambda: {p: 0.0 for p in PHASES})
    tiny_pivots: int = 0          # reference: stat->TinyPivots (pdgstrf2.c:226)
    refine_steps: int = 0         # reference: stat->RefineSteps
    peak_memory_bytes: int = 0
    current_memory_bytes: int = 0
    for_lu_bytes: int = 0         # dQuerySpace_dist analog: packed L+U
    pool_bytes: int = 0           # transient Schur update pool
    solve_report: object = None   # SolveReport of the last driver solve

    @contextlib.contextmanager
    def timer(self, phase: str):
        """TIC/TOC analog (util_dist.h:135-141)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.utime[phase] = self.utime.get(phase, 0.0) + time.perf_counter() - t0

    def log_memory(self, nbytes: int):
        """Analog of log_memory (SRC/util.c:914): delta-accounting (allocs
        positive, frees negative) with a running peak."""
        self.current_memory_bytes += nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, self.current_memory_bytes)

    def observe_memory(self, nbytes: int):
        """Replace the current gauge (the new allocation supersedes the
        previous factorization's) — keeps peak correct when one Stats is
        reused across refactorizations (the SamePattern time-stepping
        pattern)."""
        self.current_memory_bytes = nbytes
        self.peak_memory_bytes = max(self.peak_memory_bytes, nbytes)

    def gflops(self, phase: str) -> float:
        t = self.utime.get(phase, 0.0)
        return (self.ops.get(phase, 0.0) / t / 1e9) if t > 0 else 0.0

    def report(self) -> str:
        """PStatPrint analog (SRC/util.c:484-534): phase times + Mflops."""
        lines = ["**************************************************",
                 "**** Time (seconds) ****"]
        for p in PHASES:
            if self.utime.get(p, 0.0) > 0 or self.ops.get(p, 0.0) > 0:
                lines.append(f"    {p:<10s} time {self.utime.get(p, 0.0):10.4f}")
        for p in ("FACT", "SOLVE"):
            if self.ops.get(p, 0.0) > 0:
                lines.append(
                    f"    {p} flops {self.ops[p]:.6e}\tMflops {self.gflops(p) * 1e3:10.2f}")
        if self.tiny_pivots:
            lines.append(f"    tiny pivots replaced: {self.tiny_pivots}")
        if self.refine_steps:
            lines.append(f"    refinement steps: {self.refine_steps}")
        if self.solve_report is not None:
            lines.append(f"    solve health: {self.solve_report.summary()}")
        if self.for_lu_bytes:
            # dQuerySpace_dist-style report (SRC/dmemory_dist.c:73)
            lines.append(f"    L\\U storage {self.for_lu_bytes / 1e6:10.2f} MB"
                         f"\tupdate pool {self.pool_bytes / 1e6:10.2f} MB")
        if self.peak_memory_bytes:
            lines.append(
                f"    peak device memory {self.peak_memory_bytes / 1e6:10.2f} MB")
        lines.append("**************************************************")
        return "\n".join(lines)

    def print(self):
        print(self.report())
