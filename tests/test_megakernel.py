"""Bucketed mega-kernel executor (numeric/mega.py) + shape-key closure.

The contract under test is ROADMAP item 2 / ISSUE 11: the compiled-
program count must be INDEPENDENT of matrix size (the BENCH_r02 compile
wall: 119 kernels / 455 groups at n=110592, dead in `factor-compile`
before one factor FLOP), while the factors stay BITWISE identical to
the streamed and fused executors — closure and metadata padding are
index-sentinel no-ops, never arithmetic.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

pytestmark = pytest.mark.mega

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _analyzed(a, **symb_kw):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order, **symb_kw)
    return sf, sym.data[sf.value_perm], a.norm_max()


def _assert_fronts_bitwise(fa, fb):
    assert len(fa.fronts) == len(fb.fronts)
    for (l1, u1), (l2, u2) in zip(fa.fronts, fb.fronts):
        assert np.array_equal(np.asarray(l1), np.asarray(l2))
        assert np.array_equal(np.asarray(u1), np.asarray(u2))
    assert fa.tiny_pivots == fb.tiny_pivots


# ---------------------------------------------------------------------------
# the unified bucket ladder
# ---------------------------------------------------------------------------

def test_ladder_unifies_stream_and_plan_rungs():
    """One recurrence serves both historical ladders: stream._bucket_len
    reproduces the pow-2/pow-4 rounding exactly, and _bucket_sizes
    reproduces its additive-geometric rungs."""
    from superlu_dist_tpu.numeric.plan import _bucket_sizes, bucket_rung
    from superlu_dist_tpu.numeric.stream import _bucket_len

    for n, lo, base, want in [(1, 1, 2.0, 1), (3, 1, 2.0, 4),
                              (8, 8, 2.0, 8), (9, 8, 2.0, 16),
                              (24, 8, 2.0, 32), (3, 1, 4.0, 4),
                              (65, 64, 4.0, 256), (257, 64, 4.0, 1024)]:
        assert _bucket_len(n, lo, base) == want, (n, lo, base)
        assert bucket_rung(n, lo=lo, growth=base) == want
    # the plan's front-bucket rungs (min_bucket=8, growth=1.5) keep
    # their historical values below the tight top rung
    assert list(_bucket_sizes(100, 8, 1.5)) == [8, 16, 24, 40, 64, 96, 104]


def test_bucket_knobs_drive_default_ladder(monkeypatch):
    from superlu_dist_tpu.numeric.plan import bucket_rung

    monkeypatch.setenv("SLU_TPU_BUCKET_BASE", "16")
    monkeypatch.setenv("SLU_TPU_BUCKET_GROWTH", "4.0")
    assert bucket_rung(3) == 16
    assert bucket_rung(17) == 64


# ---------------------------------------------------------------------------
# shape-key closure
# ---------------------------------------------------------------------------

def test_closure_bounds_key_count_and_canonicalizes():
    """A closed plan carries at most max_keys (W, U) keys, every key a
    canonical ladder rung, and the digest is a pure function of the
    set."""
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.plan import (bucket_rung, build_plan,
                                               ladder_rungs)

    sf, _, _ = _analyzed(poisson3d(10))
    open_plan = build_plan(sf, closed=False)
    for k in (2, 4, 6):
        plan = build_plan(sf, closed=True, max_keys=k)
        assert plan.closed
        assert 1 <= len(plan.bucket_set) <= k
        for (w, u) in plan.bucket_set:
            assert w == bucket_rung(w), (w, u)
            assert u == 0 or u == bucket_rung(u), (w, u)
        assert plan.bucket_set == tuple(sorted({(g.w, g.u)
                                                for g in plan.groups}))
        plan2 = build_plan(sf, closed=True, max_keys=k)
        assert plan.bucket_set_digest() == plan2.bucket_set_digest()
    assert not open_plan.closed
    assert open_plan.bucket_set_digest() != build_plan(
        sf, closed=True, max_keys=2).bucket_set_digest()


def test_closed_plans_stay_bitwise_across_schedules():
    """Closure runs BEFORE the schedule branch (like alignment), so the
    PR 5 level/dataflow bitwise guarantee carries over to closed
    plans."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, vals, anorm = _analyzed(poisson2d(16))
    plan_l = build_plan(sf, schedule="level", closed=True)
    plan_d = build_plan(sf, schedule="dataflow", closed=True)
    f_l = numeric_factorize(plan_l, vals, anorm, executor="fused")
    f_d = numeric_factorize(plan_d, vals, anorm, executor="fused")
    widths = np.diff(sf.sn_start)
    us = np.array([len(r) for r in sf.sn_rows])
    for s in range(sf.n_supernodes):
        ga, sa = int(plan_l.sn_group[s]), int(plan_l.sn_slot[s])
        gb, sb = int(plan_d.sn_group[s]), int(plan_d.sn_slot[s])
        wr, ur = int(widths[s]), int(us[s])
        for i, (pa, pb) in enumerate(zip(f_l.fronts[ga], f_d.fronts[gb])):
            assert np.array_equal(np.asarray(pa[sa]), np.asarray(pb[sb])), \
                (s, i)


def test_env_knob_drives_closure(monkeypatch):
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, _, _ = _analyzed(poisson2d(12))
    monkeypatch.setenv("SLU_TPU_BUCKET_CLOSED", "1")
    monkeypatch.setenv("SLU_TPU_BUCKET_KEYS", "2")
    plan = build_plan(sf)
    assert plan.closed and len(plan.bucket_set) <= 2


# ---------------------------------------------------------------------------
# bitwise equivalence: mega == stream == fused
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("case,dtype", [
    ("poisson", "float32"),
    ("poisson", "float64"),
    ("hilbert", "float64"),
    ("hilbert", "complex128"),
    ("arrowhead", "float32"),
])
def test_bitwise_mega_vs_stream_vs_fused(case, dtype):
    """Same closed plan, three executors: the factored L/U panel stacks
    must match BITWISE (np.array_equal, no tolerance).  Coverage
    includes the ill-conditioned (hilbert) and structurally singular
    (rank_deficient_arrowhead, ReplaceTinyPivot path) cases."""
    from superlu_dist_tpu.models.gallery import (
        hilbert, poisson2d, rank_deficient_arrowhead)
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    a = {"poisson": lambda: poisson2d(16),
         "hilbert": lambda: hilbert(48),
         "arrowhead": lambda: rank_deficient_arrowhead(40)}[case]()
    sf, vals, anorm = _analyzed(a)
    plan = build_plan(sf, closed=True)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        vals = vals.astype(np.complex128) * (1 + 0.25j)
    f_s = numeric_factorize(plan, vals, anorm, dtype=dtype,
                            executor="stream")
    f_m = numeric_factorize(plan, vals, anorm, dtype=dtype,
                            executor="mega")
    f_f = numeric_factorize(plan, vals, anorm, dtype=dtype,
                            executor="fused")
    _assert_fronts_bitwise(f_s, f_m)
    _assert_fronts_bitwise(f_s, f_f)


def test_df64_on_closed_plan_bitwise_across_schedules():
    """The df64 executor consumes closed plans unchanged: level vs
    dataflow closed plans produce bitwise-identical emulated-double
    factors (the closure pass is schedule-invariant padding, so the
    PR 5 guarantee holds for the error-free-transform path too)."""
    from superlu_dist_tpu.models.gallery import hilbert
    from superlu_dist_tpu.numeric.df64_factor import df64_numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, vals, anorm = _analyzed(hilbert(32))
    plan_l = build_plan(sf, schedule="level", closed=True)
    plan_d = build_plan(sf, schedule="dataflow", closed=True)
    f_l = df64_numeric_factorize(plan_l, vals, anorm)
    f_d = df64_numeric_factorize(plan_d, vals, anorm)
    widths = np.diff(sf.sn_start)
    us = np.array([len(r) for r in sf.sn_rows])
    for s in range(sf.n_supernodes):
        ga, sa = int(plan_l.sn_group[s]), int(plan_l.sn_slot[s])
        gb, sb = int(plan_d.sn_group[s]), int(plan_d.sn_slot[s])
        for pa, pb in zip(f_l.fronts[ga], f_d.fronts[gb]):
            assert np.array_equal(np.asarray(pa[sa]), np.asarray(pb[sb]))


# ---------------------------------------------------------------------------
# O(1) compiled-program count
# ---------------------------------------------------------------------------

def test_kernel_count_constant_in_n():
    """The gate invariant (scripts/compile_census.py --buckets): under
    the bench blocking the closed mega program count is the SAME at
    every gallery size, while the streamed per-key count grows."""
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.numeric.stream import StreamExecutor

    counts, stream_counts = [], []
    for nx in (12, 16, 20):
        sf, _, _ = _analyzed(poisson3d(nx), relax=128, max_supernode=256,
                             amalg_tol=1.05)
        plan = build_plan(sf, min_bucket=16, growth=1.05, closed=True)
        counts.append(MegaExecutor(plan, "float32").n_kernels)
        stream_counts.append(StreamExecutor(plan, "float32").n_kernels)
    assert len(set(counts)) == 1, counts
    assert counts[-1] <= stream_counts[-1]
    assert stream_counts[-1] > stream_counts[0] or \
        counts[-1] < stream_counts[-1]


def test_mega_accepts_mesh():
    import jax
    from jax.sharding import Mesh

    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.factor import get_executor
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, _, _ = _analyzed(poisson2d(10))
    plan = build_plan(sf, closed=True)
    devs = np.array(jax.devices()[:2]).reshape(2, 1)
    mesh = Mesh(devs, ("snode", "panel"))
    # mega composes under a mesh now (GSPMD-sharded bucket programs) —
    # an explicit mega request keeps the MegaExecutor instead of
    # downgrading to stream; tests/test_spmd.py pins the numerics
    ex = MegaExecutor(plan, "float64", mesh=mesh)
    assert ex.mesh is mesh
    ex = get_executor(plan, "float64", executor="mega", mesh=mesh)
    assert isinstance(ex, MegaExecutor) and ex.mesh is mesh
    with pytest.raises(ValueError):
        get_executor(plan, "float64", executor="bogus")


def test_executor_knob_through_driver(monkeypatch):
    """SLU_TPU_EXECUTOR=mega + SLU_TPU_BUCKET_CLOSED=1 steer a full
    gssvx solve through the mega executor and still hit reference
    accuracy."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.mega import MegaExecutor

    monkeypatch.setenv("SLU_TPU_EXECUTOR", "mega")
    monkeypatch.setenv("SLU_TPU_BUCKET_CLOSED", "1")
    a = poisson2d(12)
    xt = np.random.default_rng(3).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0
    assert lu.plan.closed
    assert any(isinstance(fn, MegaExecutor)
               for fn in lu.plan._factor_fns.values())
    assert np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b) < 1e-12


# ---------------------------------------------------------------------------
# checkpoint -> interrupt -> resume, bitwise, executor-portable
# ---------------------------------------------------------------------------

def test_mega_checkpoint_resume_bitwise_and_portable(tmp_path):
    """A mega run interrupted at a group boundary resumes BITWISE — and
    because frontiers store the UNPADDED pool, the same checkpoint also
    resumes under the streamed executor (deployment can switch
    executors mid-recovery)."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    sf, vals, anorm = _analyzed(poisson2d(20))
    plan = build_plan(sf, closed=True)
    ref = numeric_factorize(plan, vals, anorm, executor="mega")
    assert len(plan.groups) >= 5
    for resume_exec in ("mega", "stream"):
        ck = str(tmp_path / f"ck_{resume_exec}")
        with pytest.raises(DeadlineExceededError):
            numeric_factorize(plan, vals, anorm, executor="mega",
                              ckpt_dir=ck, ckpt_every=1,
                              deadline=CountdownDeadline(3))
        res = numeric_factorize(plan, vals, anorm, executor=resume_exec,
                                resume_from=ck)
        assert res.resumed_groups > 0
        _assert_fronts_bitwise(ref, res)


# ---------------------------------------------------------------------------
# warm start: two-run subprocess pair against one persistent cache
# ---------------------------------------------------------------------------

_WARM_CHILD = """
import sys, json
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
enable_compile_cache(sys.argv[1])
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
a = poisson2d(24)
sym = symmetrize_pattern(a)
sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
plan = build_plan(sf, closed=True)
numeric_factorize(plan, sym.data[sf.value_perm], a.norm_max(),
                  executor="mega")
blk = COMPILE_STATS.block()
recs = [r for r in COMPILE_STATS.records if r.site == "mega._kernel"]
print(json.dumps({
    "digest": plan.bucket_set_digest(),
    "seconds": blk["seconds"],
    "fresh": blk["fresh_seconds"],
    "xla": sum(r.compile_seconds or 0.0 for r in recs),
    "hits": blk["persistent_hits"],
    "builds": len(recs)}))
"""


def test_warm_start_second_run_compiles_nothing(tmp_path):
    """The acceptance pair (ISSUE 11): two subprocess runs of the SAME
    matrix against one persistent cache.  The second run's FRESH
    compile seconds (time on programs the cache did not serve) must be
    < 5% of the cold run's — it is exactly 0.0 when every program disk-
    hits — and the XLA compile stage must collapse too."""
    child = tmp_path / "warm_child.py"
    child.write_text(_WARM_CHILD)
    cache = str(tmp_path / "jaxcache")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    rows = []
    for _ in range(2):
        r = subprocess.run([sys.executable, str(child), cache], env=env,
                           stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        assert r.returncode == 0, r.stderr.decode()
        rows.append(json.loads(r.stdout.decode().strip().splitlines()[-1]))
    cold, warm = rows
    assert cold["digest"] == warm["digest"]
    assert cold["builds"] == warm["builds"] > 0
    assert cold["hits"] == 0 and warm["hits"] == warm["builds"]
    assert cold["fresh"] > 0
    assert warm["fresh"] < 0.05 * cold["fresh"], (cold, warm)
    assert warm["xla"] < 0.5 * cold["xla"], (cold, warm)


def test_warm_compile_cache_prebake(tmp_path):
    """scripts/warm_compile_cache.py prebakes a closed bucket set with
    ZERO factorization work and marks it warm; a MegaExecutor built
    afterwards in the same process reuses the census-accounted
    programs."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import warm_compile_cache as wcc
    finally:
        sys.path.pop(0)
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.utils import jaxcache

    sf, _, _ = _analyzed(poisson2d(12))
    plan = build_plan(sf, closed=True)
    row = wcc.warm_plan(plan, "float64")
    assert row["n_kernels"] == len(plan.bucket_set)
    assert row["bucket_set_digest"] == plan.bucket_set_digest()
    assert jaxcache.bucket_set_warm(plan.bucket_set_digest())


# ---------------------------------------------------------------------------
# census pending-key accounting (the watchdog postmortem bugfix)
# ---------------------------------------------------------------------------

def test_census_pending_keys_name_uncompiled_buckets():
    """Executors announce their full expected kernel set; record()
    retires keys as they build — the delta is the `pending_kernels`
    list a factor-compile watchdog row emits so the postmortem names
    the offenders (the BENCH_r02 gap)."""
    import time

    from superlu_dist_tpu.obs.compilestats import CompileStats

    cs = CompileStats()
    cs.announce("mega._kernel", ["lu b4 m64 w32 u32", "lu b8 m96 w64 u32"])
    assert {p["key"] for p in cs.pending()} == {"lu b4 m64 w32 u32",
                                                "lu b8 m96 w64 u32"}
    t0 = time.perf_counter()
    cs.record("mega._kernel", "lu b4 m64 w32 u32", t0, 0.1)
    assert [p["key"] for p in cs.pending()] == ["lu b8 m96 w64 u32"]
    # a built key is never re-announced (warmed executor, same plan)
    cs.announce("mega._kernel", ["lu b4 m64 w32 u32"])
    assert [p["key"] for p in cs.pending()] == ["lu b8 m96 w64 u32"]
    cs.record("mega._kernel", "lu b8 m96 w64 u32", t0, 0.1)
    assert cs.pending() == []


def test_executors_announce_their_key_sets():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS

    sf, vals, anorm = _analyzed(poisson2d(14))
    plan = build_plan(sf, closed=True)
    ex = MegaExecutor(plan, "float64")
    mine = [p for p in COMPILE_STATS.pending()
            if p["site"] == "mega._kernel"]
    # every one of this executor's not-yet-built buckets is pending
    labels = {ex._census_label(key) for key, _, _, _, _ in ex._steps}
    unbuilt = labels - {r.key for r in COMPILE_STATS.records
                        if r.site == "mega._kernel"}
    assert unbuilt <= {p["key"] for p in mine}
    # factorizing retires them
    import jax.numpy as jnp
    ex(jnp.asarray(vals), jnp.asarray(np.float64(1e-10)))
    after = {p["key"] for p in COMPILE_STATS.pending()
             if p["site"] == "mega._kernel"}
    assert not (labels & after)


# ---------------------------------------------------------------------------
# bench row acceptance fields (subprocess, mega granularity)
# ---------------------------------------------------------------------------

def test_bench_row_carries_mega_census_fields(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NX="6",
               BENCH_REPS="1", BENCH_NO_PROBE="1", BENCH_FORCE_CPU="1",
               BENCH_DEADLINE_S="420", BENCH_GRANULARITY="mega",
               BENCH_SOLVE_NRHS="")
    env.pop("SLU_TPU_TRACE", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, cwd=REPO, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    assert r.returncode == 0, r.stderr.decode()
    row = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert row["value"] is not None
    assert row["granularity"] == "mega"
    assert row["bucket_closed"] is True
    assert row["n_kernels"] == row["n_kernels_compiled"] > 0
    assert isinstance(row["bucket_set_digest"], str)
    assert row["compile_seconds"] >= row.get("xla_compile_seconds", 0) > 0
    assert "compile_fresh_seconds" in row
    # nothing left pending after a completed factor-compile phase
    assert "pending_kernels" not in row
