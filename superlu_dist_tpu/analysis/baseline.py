"""Committed JSON baseline for slulint findings.

The gate (scripts/run_slulint.sh) must fail only on NEW findings, so
known ones are grandfathered in a committed baseline file.  Entries are
keyed by (rule, normalized path, fingerprint of the flagged source
line), NOT by line number — findings survive unrelated edits above them
and go stale only when the flagged line itself changes (at which point
the author must re-justify or fix).

The project's target state is an EMPTY baseline: real findings get fixed
or carry an inline ``# slulint: disable=SLUxxx`` with a justification.
The baseline exists for the migration window after a new rule lands.
"""

from __future__ import annotations

import hashlib
import json
import os

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".slulint-baseline.json"


def _norm_path(path: str, root: str | None = None) -> str:
    if root:
        try:
            path = os.path.relpath(path, root)
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def fingerprint(source: str, line: int) -> str:
    """Hash of the flagged line with whitespace collapsed (indentation
    changes and reformatting don't invalidate the entry)."""
    lines = source.splitlines()
    text = lines[line - 1] if 1 <= line <= len(lines) else ""
    return hashlib.sha256(" ".join(text.split()).encode()).hexdigest()[:16]


def entry(finding, source: str, root: str | None = None) -> dict:
    return {"rule": finding.rule,
            "path": _norm_path(finding.path, root),
            "fingerprint": fingerprint(source, finding.line)}


def write(path: str, entries) -> None:
    doc = {"version": BASELINE_VERSION,
           "findings": sorted(entries, key=lambda e: (e["path"], e["rule"],
                                                      e["fingerprint"]))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")


def load(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{doc.get('version')!r}")
    return list(doc.get("findings", []))


def filter_new(findings, sources: dict, baseline_entries,
               root: str | None = None):
    """Split findings into (new, baselined).  Each baseline entry
    absorbs at most one finding (a multiset match), so adding a second
    identical-looking violation on a changed line still fails the gate."""
    budget: dict = {}
    for e in baseline_entries:
        key = (e["rule"], e["path"], e["fingerprint"])
        budget[key] = budget.get(key, 0) + 1
    new, old = [], []
    for f in findings:
        key = (f.rule, _norm_path(f.path, root),
               fingerprint(sources[f.path], f.line))
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
