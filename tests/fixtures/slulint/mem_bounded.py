"""SLU121 clean twin of mem_blowup.py: the same arithmetic volume as a
sequential chain — each intermediate dies at the next equation, so the
high-water mark stays ~2 buffers no matter how long the chain gets.
``build()`` returns ``(jitted_fn, args)`` with the same f32[256,256]
buffer size."""
import jax
import jax.numpy as jnp


def build():
    def chain(x):
        y = x * 2.0        # x dies here
        y = y * 3.0
        y = y * 4.0
        return jnp.sum(y)

    args = (jnp.zeros((256, 256), jnp.float32),)
    return jax.jit(chain), args
