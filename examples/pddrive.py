#!/usr/bin/env python
"""Basic expert-driver example — analog of EXAMPLE/pddrive.c:51.

Solve A·x = b once with default options, then verify against the
fabricated xtrue (the reference example's pdinf_norm_error check,
pddrive.c:235).

    python examples/pddrive.py [matrix.rua] [--backend cpu]
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu

    a, src = load_matrix()
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    xtrue, b = make_rhs(a)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0, f"info={info}"
    resid = report("pddrive", a, b, x, xtrue, stats)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
