"""Supernodal triangular solves (host path).

Capability analog of pdgstrs (SRC/pdgstrs.c:838) + the lsum kernels
(SRC/pdgstrs_lsum.c): forward solve L·y = d level-by-level up the supernode
tree, backward solve U·x = y back down.  The reference's distributed solve
is an MPI event loop over per-supernode broadcast/reduce trees; the tree
dependencies here are the same supernode levels the factorization batches
on, so the host loop visits supernodes in level order — and a device-side
batched version (large nrhs) can reuse the same plan (future work, mirrors
the reference offloading Linv GEMMs only when nrhs is large, SURVEY.md §7
hard-part 5).

Solves run in float64 on the host regardless of factor dtype: factors are
promoted on pull, which costs nothing extra at solve time and keeps
iterative refinement's correction solves stable.
"""

from __future__ import annotations

import numpy as np
from scipy.linalg import solve_triangular

from superlu_dist_tpu.numeric.factor import NumericFactorization


def _promote(fact, rhs):
    return np.array(rhs, dtype=np.promote_types(
        np.asarray(rhs).dtype,
        np.float64 if not np.issubdtype(fact.dtype, np.complexfloating)
        else np.complex128))


def lu_solve_trans(fact: NumericFactorization, rhs: np.ndarray,
                   conj: bool = False) -> np.ndarray:
    """Solve (L·U)ᵀ x = rhs (or (L·U)ᴴ x with conj=True), permuted labeling.

    The reference solves AᵀX = B through the same factors (trans_t,
    superlu_defs.h:628-657): Mᵀ = Uᵀ·Lᵀ, so the forward sweep is with Uᵀ
    (lower triangular) walking supernodes ascending, the backward sweep
    with Lᵀ (unit upper) descending — the mirror of lu_solve using the U12
    blocks on the way down and L21 on the way up.
    """
    plan = fact.plan
    sf = plan.sf
    hosts = fact.pull_to_host()
    y = _promote(fact, rhs)
    squeeze = y.ndim == 1
    if squeeze:
        y = y[:, None]
    ns = sf.n_supernodes
    first = sf.sn_start[:-1]
    last = sf.sn_start[1:] - 1

    def blocks(s):
        grp = plan.groups[plan.sn_group[s]]
        lp, up = hosts[plan.sn_group[s]]
        lp, up = lp[plan.sn_slot[s]], up[plan.sn_slot[s]]
        w = int(last[s] - first[s] + 1)
        u = len(sf.sn_rows[s])
        W = grp.w
        f11 = lp[:w, :w]
        l21 = lp[W:W + u, :w]
        u12 = up[:w, :u]
        if conj:
            f11, l21, u12 = f11.conj(), l21.conj(), u12.conj()
        return f11, l21, u12, w, u

    # forward: Uᵀ y = d, supernodes ascending (Uᵀ is lower triangular)
    for s in range(ns):
        f11, l21, u12, w, u = blocks(s)
        cols = slice(int(first[s]), int(last[s]) + 1)
        # triangular solve, not LAPACK getrf (np.linalg.solve): the
        # per-supernode blocks make this loop the whole solve cost
        yj = solve_triangular(f11, y[cols], trans=1, lower=False,
                              check_finite=False)
        y[cols] = yj
        if u:
            y[sf.sn_rows[s]] -= u12.astype(yj.dtype).T @ yj

    # backward: Lᵀ x = y, descending (Lᵀ is unit upper triangular)
    for s in range(ns - 1, -1, -1):
        f11, l21, u12, w, u = blocks(s)
        cols = slice(int(first[s]), int(last[s]) + 1)
        t = y[cols]
        if u:
            t = t - l21.astype(t.dtype).T @ y[sf.sn_rows[s]]
        y[cols] = solve_triangular(f11, t, trans=1, lower=True,
                                   unit_diagonal=True, check_finite=False)

    return y[:, 0] if squeeze else y


def lu_solve(fact: NumericFactorization, rhs: np.ndarray) -> np.ndarray:
    """Solve (L·U) x = rhs for rhs (n,) or (n, k), in the factor's permuted
    labeling."""
    plan = fact.plan
    sf = plan.sf
    hosts = fact.pull_to_host()
    y = _promote(fact, rhs)
    squeeze = y.ndim == 1
    if squeeze:
        y = y[:, None]
    ns = sf.n_supernodes
    first = sf.sn_start[:-1]
    last = sf.sn_start[1:] - 1

    def blocks(s):
        grp = plan.groups[plan.sn_group[s]]
        lp, up = hosts[plan.sn_group[s]]
        lp, up = lp[plan.sn_slot[s]], up[plan.sn_slot[s]]
        w = int(last[s] - first[s] + 1)
        u = len(sf.sn_rows[s])
        W = grp.w
        f11 = lp[:w, :w]
        l21 = lp[W:W + u, :w]
        u12 = up[:w, :u]
        return f11, l21, u12, w, u

    # forward: supernodes in column order = topological (children first)
    for s in range(ns):
        f11, l21, u12, w, u = blocks(s)
        cols = slice(int(first[s]), int(last[s]) + 1)
        yj = solve_triangular(f11, y[cols], lower=True,
                              unit_diagonal=True, check_finite=False)
        y[cols] = yj
        if u:
            y[sf.sn_rows[s]] -= l21.astype(yj.dtype) @ yj

    # backward: reverse order (parents before children)
    for s in range(ns - 1, -1, -1):
        f11, l21, u12, w, u = blocks(s)
        cols = slice(int(first[s]), int(last[s]) + 1)
        t = y[cols]
        if u:
            t = t - u12.astype(t.dtype) @ y[sf.sn_rows[s]]
        y[cols] = solve_triangular(f11, t, lower=False,
                                   check_finite=False)

    return y[:, 0] if squeeze else y
