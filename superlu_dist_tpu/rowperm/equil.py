"""Equilibration: row/column scaling so that max |row| and |col| are ~1.

Analogs of pdgsequ (SRC/pdgsequ.c:86) and pdlaqgs (SRC/pdlaqgs.c), which
follow LAPACK dgeequ/dlaqgs semantics: R_i = 1/max_j|a_ij|,
C_j = 1/max_i(R_i |a_ij|); scaling is applied only when the row/col
condition estimates or the matrix magnitude warrant it (THRESH=0.1).
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR
from superlu_dist_tpu.utils.errors import SuperLUError

_THRESH = 0.1


def gsequ(a: SparseCSR):
    """Compute scalings (r, c, rowcnd, colcnd, amax).  pdgsequ analog."""
    n, m = a.shape
    rows = np.repeat(np.arange(n), np.diff(a.indptr))
    absa = np.abs(a.data)
    rowmax = np.zeros(n)
    np.maximum.at(rowmax, rows, absa)
    if np.any(rowmax == 0):
        raise SuperLUError(f"row {int(np.argmin(rowmax != 0))} of A is exactly zero")
    r = 1.0 / rowmax
    colmax = np.zeros(m)
    np.maximum.at(colmax, a.indices, absa * r[rows])
    if np.any(colmax == 0):
        raise SuperLUError(f"column {int(np.argmin(colmax != 0))} of A is exactly zero")
    c = 1.0 / colmax
    smlnum = np.finfo(np.float64).tiny
    bignum = 1.0 / smlnum
    rowcnd = max(r.min(), smlnum) / min(r.max(), bignum)
    colcnd = max(c.min(), smlnum) / min(c.max(), bignum)
    amax = float(absa.max(initial=0.0))
    return r, c, float(rowcnd), float(colcnd), amax


def laqgs(a: SparseCSR, r, c, rowcnd, colcnd, amax):
    """Decide + apply scaling; returns (A_scaled, equed) with equed in
    {'N','R','C','B'} — pdlaqgs analog (LAPACK dlaqgs decision rule)."""
    small = np.finfo(np.float64).tiny / np.finfo(np.float64).eps
    large = 1.0 / small
    do_row = rowcnd < _THRESH
    do_col = colcnd < _THRESH or amax < small or amax > large
    if not do_row and not do_col:
        return a, "N"
    out = a
    if do_row:
        out = out.row_scale(r)
    if do_col:
        out = out.col_scale(c)
    equed = {(True, False): "R", (False, True): "C", (True, True): "B"}[(do_row, do_col)]
    return out, equed
