import os

import numpy as np
import pytest

from superlu_dist_tpu.io.readers import (
    read_harwell_boeing, read_matrix_market, read_triples, read_binary,
    write_binary, write_matrix_market, read_matrix,
)
from superlu_dist_tpu.models.gallery import random_sparse

REF = "/root/reference/EXAMPLE"

MM_TEXT = """%%MatrixMarket matrix coordinate real general
% comment
3 3 5
1 1 2.0
2 2 3.0
3 3 4.0
1 3 -1.0
3 1 -1.5
"""

MM_SYM = """%%MatrixMarket matrix coordinate real symmetric
2 2 3
1 1 2.0
2 1 -1.0
2 2 2.0
"""

TRIPLES = """3 4
1 1 1.0
2 2 2.0
3 3 3.0
1 3 -1.0
"""


def test_matrix_market_general():
    a = read_matrix_market(MM_TEXT)
    want = np.array([[2.0, 0, -1.0], [0, 3.0, 0], [-1.5, 0, 4.0]])
    np.testing.assert_allclose(a.to_dense(), want)


def test_matrix_market_symmetric():
    a = read_matrix_market(MM_SYM)
    want = np.array([[2.0, -1.0], [-1.0, 2.0]])
    np.testing.assert_allclose(a.to_dense(), want)


def test_triples():
    a = read_triples(TRIPLES)
    want = np.zeros((3, 3))
    want[0, 0], want[1, 1], want[2, 2], want[0, 2] = 1, 2, 3, -1
    np.testing.assert_allclose(a.to_dense(), want)


def test_binary_roundtrip(tmp_path):
    a = random_sparse(20, density=0.1, seed=7)
    p = tmp_path / "m.bin"
    write_binary(p, a)
    b = read_binary(p)
    np.testing.assert_allclose(b.to_dense(), a.to_dense())


def test_mm_roundtrip(tmp_path):
    a = random_sparse(15, density=0.1, seed=8, dtype=np.complex128)
    p = tmp_path / "m.mtx"
    write_matrix_market(p, a)
    b = read_matrix(p)
    np.testing.assert_allclose(b.to_dense(), a.to_dense(), atol=1e-14)


@pytest.mark.skipif(not os.path.exists(f"{REF}/g20.rua"), reason="no reference fixtures")
def test_read_g20():
    a = read_harwell_boeing(f"{REF}/g20.rua")
    assert a.shape == (400, 400)
    assert a.nnz == 1920
    d = a.to_dense()
    assert np.all(np.diag(d) != 0) or True  # just sanity: finite values
    assert np.isfinite(d).all()


@pytest.mark.skipif(not os.path.exists(f"{REF}/cg20.cua"), reason="no reference fixtures")
def test_read_cg20_complex():
    a = read_harwell_boeing(f"{REF}/cg20.cua")
    assert a.shape == (400, 400)
    assert a.nnz == 1920
    assert np.issubdtype(a.data.dtype, np.complexfloating)


@pytest.mark.skipif(not os.path.exists(f"{REF}/big.rua"), reason="no reference fixtures")
def test_read_big():
    a = read_harwell_boeing(f"{REF}/big.rua")
    assert a.shape == (4960, 4960)
    assert np.isfinite(np.abs(a.data)).all()
