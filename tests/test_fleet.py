"""Serving fleet (serve/fleet.py + serve/handlecache.py): multi-handle
replicas over an LRU handle cache, health-checked routing, zero-loss
failover with bitwise-identical re-routed results, fleet backpressure,
and rolling deploy with canary-gated rollback — the chaos specs
``kill_replica`` / ``quarantine_replica`` / ``slow_replica`` driving
the failure domains deterministically."""

import os
import threading
import time

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.persist.serial import lu_meta, save_lu
from superlu_dist_tpu.serve import (DeployRollbackError, FleetRouter,
                                    HandleCache, ReplicaFailureError,
                                    ServeDeadlineError,
                                    ServeOverloadError,
                                    ServerClosedError, SolveServer)
from superlu_dist_tpu.serve.fleet import FLEET_SERVER_KW
from superlu_dist_tpu.utils.errors import SuperLUError
from superlu_dist_tpu.utils.options import IterRefine, Options

pytestmark = pytest.mark.fleet

KEYS = ("m0", "m1", "m2")
_NX = {"m0": 6, "m1": 7, "m2": 8}


def _factor(a):
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, np.ones(a.n_rows))
    assert info == 0
    return lu


@pytest.fixture(scope="module")
def bundles(tmp_path_factory):
    """Three distinct matrices persisted as bundles + their operators."""
    root = tmp_path_factory.mktemp("fleet_bundles")
    paths, mats = {}, {}
    for key in KEYS:
        a = poisson2d(_NX[key])
        d = str(root / key)
        save_lu(_factor(a), d)
        paths[key] = d
        mats[key] = a
    return paths, mats


def _mixed_stream(fleet, mats, n_tickets=18, seed=0, keys=KEYS):
    """Submit a deterministic mixed stream over ``keys``; returns the
    tickets in submission order."""
    rng = np.random.default_rng(seed)
    tickets = []
    for j in range(n_tickets):
        key = keys[j % len(keys)]
        a = mats[key]
        b = a.matvec(rng.standard_normal(a.n_rows))
        tickets.append(fleet.submit(key, b))
    return tickets


# ---------------------------------------------------------------------------
# routing + multi-handle basics
# ---------------------------------------------------------------------------

def test_mixed_stream_round_trip(bundles):
    """One fleet serves a mixed stream of three distinct matrices, each
    request solved against the right handle."""
    paths, mats = bundles
    fleet = FleetRouter(paths, n_replicas=2, kind="thread")
    rng = np.random.default_rng(1)
    recs = []
    for j in range(12):
        key = KEYS[j % 3]
        a = mats[key]
        xt = rng.standard_normal(a.n_rows)
        recs.append((key, xt, fleet.submit(key, a.matvec(xt))))
    for key, xt, t in recs:
        got = t.result(120)
        res = np.linalg.norm(got - xt) / np.linalg.norm(xt)
        assert res < 1e-4, (key, res)    # f32 factors
        assert t.attempts == 1
    st = fleet.stats()
    fleet.close()
    assert st["requests"] == 12 and st["delivered"] == 12
    assert st["errors"] == 0 and st["failovers"] == 0
    assert st["replicas_healthy"] == 2


def test_unknown_key_and_closed_fleet(bundles):
    paths, mats = bundles
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1, kind="thread")
    with pytest.raises(SuperLUError):
        fleet.submit("nope", np.ones(mats["m0"].n_rows))
    fleet.close()
    with pytest.raises(ServerClosedError):
        fleet.submit("m0", np.ones(mats["m0"].n_rows))


# ---------------------------------------------------------------------------
# handle cache: LRU eviction + scrub-verified reload
# ---------------------------------------------------------------------------

def test_handle_cache_lru_eviction_and_scrub_reload(bundles):
    """Under a byte budget sized for two of three bundles, loading the
    third evicts the least-recently-used idle handle; reloading it
    round-trips BITWISE (digest-verified load + scrub pass against the
    manifest)."""
    paths, mats = bundles
    nb = {k: lu_meta(p)["nbytes"] for k, p in paths.items()}
    budget = nb["m0"] + nb["m1"] + 100
    cache = HandleCache(budget, FLEET_SERVER_KW)
    for k, p in paths.items():
        cache.register(k, p)
    b0 = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
    srv0 = cache.get("m0")
    ref = srv0.solve(b0, 120)
    assert cache.get("m0") is srv0        # resident hit
    cache.get("m1")
    cache.get("m2")                       # must push past the budget
    st = cache.stats()
    assert st["evictions"] >= 1
    assert "m0" not in cache.resident()   # LRU victim
    again = cache.get("m0").solve(b0, 120)
    np.testing.assert_array_equal(ref, again)
    st = cache.stats()
    assert st["loads"] == 4 and st["hits"] == 1
    cache.close()


def test_handle_cache_busy_entries_survive_eviction(bundles):
    """A resident handle with queued/in-flight work is never evicted —
    the cache runs over budget instead of dropping tickets (the
    ``SolveServer.idle()`` eviction predicate)."""
    paths, mats = bundles
    cache = HandleCache(1, FLEET_SERVER_KW)   # absurdly tight budget
    cache.register("m0", paths["m0"])
    cache.register("m1", paths["m1"])
    srv = cache.get("m0")
    srv.idle = lambda: False              # pin it busy
    cache.get("m1")                       # would evict m0 if it could
    assert "m0" in cache.resident()       # busy handles survive
    assert cache.stats()["resident_bytes"] > cache.budget_bytes
    cache.close()


def test_handle_cache_unknown_key(bundles):
    cache = HandleCache(0, FLEET_SERVER_KW)
    with pytest.raises(SuperLUError):
        cache.get("never-registered")
    cache.close()


# ---------------------------------------------------------------------------
# zero-loss failover
# ---------------------------------------------------------------------------

def _run_stream(paths, mats, chaos=None, n_replicas=3, n_tickets=18,
                monkeypatch=None, **kw):
    if chaos is not None:
        monkeypatch.setenv("SLU_TPU_CHAOS", chaos)
    else:
        os.environ.pop("SLU_TPU_CHAOS", None)
    fleet = FleetRouter(paths, n_replicas=n_replicas, kind="thread",
                        **kw)
    try:
        tickets = _mixed_stream(fleet, mats, n_tickets=n_tickets,
                                keys=tuple(paths))
        xs = [t.result(180) for t in tickets]
        return xs, fleet.stats()
    finally:
        fleet.close()
        if chaos is not None:
            monkeypatch.delenv("SLU_TPU_CHAOS", raising=False)


def test_replica_kill_mid_stream_zero_loss_bitwise(bundles,
                                                   monkeypatch):
    """THE headline guarantee: a replica killed mid-stream loses zero
    accepted tickets, and every delivered X is bitwise identical to an
    undisturbed run of the same stream."""
    paths, mats = bundles
    ref, st0 = _run_stream(paths, mats, monkeypatch=monkeypatch)
    assert st0["failovers"] == 0
    got, st1 = _run_stream(paths, mats, chaos="kill_replica=1@batch=2",
                           monkeypatch=monkeypatch)
    assert st1["failovers"] >= 1, "the kill never fired"
    assert st1["replicas_failed"] == [1]
    assert st1["errors"] == 0 and st1["delivered"] == len(ref)
    assert st1["reroutes"] >= 1
    drift = [i for i, (r, g) in enumerate(zip(ref, got))
             if not np.array_equal(r, g)]
    assert not drift, (
        f"re-routed ticket(s) {drift} are not bitwise identical to the "
        "undisturbed run")


def test_quarantine_replica_reroutes_without_client_errors(bundles,
                                                           monkeypatch):
    paths, mats = bundles
    got, st = _run_stream(paths, mats, chaos="quarantine_replica=0",
                          n_replicas=2, n_tickets=9,
                          monkeypatch=monkeypatch)
    assert st["errors"] == 0 and st["delivered"] == 9
    assert st["failovers"] >= 1 and st["reroutes"] >= 1
    assert st["replicas_failed"] == []   # quarantined, not dead


def test_slow_replica_zero_false_positive_failovers(bundles,
                                                    monkeypatch):
    """Liveness is judged on the process/thread, never on latency: a
    stalled replica is waited out, not failed over."""
    paths, mats = bundles
    got, st = _run_stream(paths, mats, chaos="slow_replica=0,secs=0.6",
                          n_replicas=2, n_tickets=8, health_s=0.02,
                          monkeypatch=monkeypatch)
    assert st["failovers"] == 0 and st["reroutes"] == 0
    assert st["errors"] == 0 and st["delivered"] == 8


def test_no_healthy_replica_left_structured_error(bundles, monkeypatch):
    """When the LAST replica dies, undelivered tickets get a structured
    ReplicaFailureError — never a hang."""
    paths, mats = bundles
    monkeypatch.setenv("SLU_TPU_CHAOS", "kill_replica=0@batch=1")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1, kind="thread")
    try:
        b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
        tickets = [fleet.submit("m0", b) for _ in range(4)]
        outcomes = []
        for t in tickets:
            try:
                t.result(60)
                outcomes.append("ok")
            except ReplicaFailureError:
                outcomes.append("rfail")
        assert "rfail" in outcomes
        assert outcomes.count("ok") >= 1      # batch 0 was served
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# backpressure + deadlines
# ---------------------------------------------------------------------------

def test_fleet_shed_at_cap(bundles, monkeypatch):
    paths, mats = bundles
    # stall the only replica so the backlog provably exceeds the cap
    monkeypatch.setenv("SLU_TPU_CHAOS", "slow_replica=0,secs=0.3")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1,
                        kind="thread", queue_max=4)
    try:
        b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
        wide = np.stack([b] * 3, axis=1)
        ok, shed = [], 0
        for _ in range(5):
            try:
                ok.append(fleet.submit("m0", wide))
            except ServeOverloadError as e:
                assert e.reason == "fleet_queue_full"
                shed += 1
        assert shed > 0, "the fleet cap never engaged"
        for t in ok:
            t.result(120)
        assert fleet.stats()["shed"] == shed
    finally:
        fleet.close()


def test_fleet_drain_sheds_and_finishes(bundles, monkeypatch):
    paths, mats = bundles
    monkeypatch.setenv("SLU_TPU_CHAOS", "slow_replica=0,secs=0.2")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1, kind="thread")
    try:
        b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
        t = fleet.submit("m0", b)
        done = fleet.drain(timeout=60)
        assert done and t.done()
        with pytest.raises(ServeOverloadError) as ei:
            fleet.submit("m0", b)
        assert ei.value.reason == "draining"
        fleet.resume()
        fleet.solve("m0", b, timeout=60)
    finally:
        fleet.close()


def test_fleet_deadline_end_to_end(bundles, monkeypatch):
    """A ticket undelivered past SLU_TPU_FLEET_DEADLINE_MS expires with
    ServeDeadlineError even while a replica is stalled."""
    paths, mats = bundles
    monkeypatch.setenv("SLU_TPU_CHAOS", "slow_replica=0,secs=1.0")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1,
                        kind="thread", deadline_s=0.1, health_s=0.02)
    try:
        b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
        tickets = [fleet.submit("m0", b) for _ in range(3)]
        verdicts = []
        for t in tickets:
            try:
                t.result(30)
                verdicts.append("ok")
            except ServeDeadlineError:
                verdicts.append("deadline")
        assert "deadline" in verdicts, verdicts
        assert fleet.stats()["deadline_miss"] >= 1
        assert fleet.stats()["failovers"] == 0   # slow, not dead
    finally:
        fleet.close()


def test_close_delivers_structured_error(bundles, monkeypatch):
    paths, mats = bundles
    monkeypatch.setenv("SLU_TPU_CHAOS", "slow_replica=0,secs=0.5")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=1, kind="thread")
    b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
    tickets = [fleet.submit("m0", b) for _ in range(4)]
    fleet.close()
    for t in tickets:
        try:
            t.result(10)      # served before close: fine
        except (ServerClosedError, ReplicaFailureError):
            pass              # undelivered at close: structured, no hang


# ---------------------------------------------------------------------------
# rolling deploy
# ---------------------------------------------------------------------------

def _poisoned_bundle(mats, tmp_path, name="poisoned"):
    lu = _factor(mats["m0"])
    lp, up = lu.numeric.fronts[0]
    lu.numeric.fronts[0] = (np.asarray(lp) * np.nan, up)
    d = str(tmp_path / name)
    save_lu(lu, d)
    return d


def test_rolling_deploy_and_poisoned_rollback(bundles, tmp_path):
    paths, mats = bundles
    a = mats["m0"]
    good2 = str(tmp_path / "m0_v2")
    save_lu(_factor(a), good2)
    bad = _poisoned_bundle(mats, tmp_path)
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=2, kind="thread")
    try:
        b = a.matvec(np.ones(a.n_rows))
        ref = fleet.solve("m0", b, timeout=120)
        out = fleet.deploy(good2, a=a, berr_max=1e-4)
        assert out["replicas_swapped"] == [0, 1]
        assert fleet.stats()["deploys"] == 1
        # same matrix, fresh identical factorization → bitwise X
        np.testing.assert_array_equal(ref,
                                      fleet.solve("m0", b, timeout=120))
        # poisoned bundle: the preflight canary rejects it with ZERO
        # replica exposure
        with pytest.raises(DeployRollbackError) as ei:
            fleet.deploy(bad)
        assert ei.value.stage == "canary"
        assert ei.value.rolled_back == []
        assert fleet.stats()["rollbacks"] == 1
        np.testing.assert_array_equal(ref,
                                      fleet.solve("m0", b, timeout=120))
    finally:
        fleet.close()


def test_rolling_deploy_mid_replica_rollback_restores(bundles,
                                                      tmp_path):
    """With the preflight gate off, the poisoned bundle reaches replica
    0, its canary fails, and the rollback RESTORES the already-swapped
    replica — the fleet keeps serving the old factors bitwise."""
    paths, mats = bundles
    a = mats["m0"]
    bad = _poisoned_bundle(mats, tmp_path, "poisoned2")
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=2, kind="thread")
    try:
        b = a.matvec(np.ones(a.n_rows))
        ref = fleet.solve("m0", b, timeout=120)
        with pytest.raises(DeployRollbackError) as ei:
            fleet.deploy(bad, preflight=False)
        assert ei.value.stage == "canary" and ei.value.replica == 0
        assert ei.value.rolled_back == [0]
        np.testing.assert_array_equal(ref,
                                      fleet.solve("m0", b, timeout=120))
        # traffic still flows after the rollback on every replica
        for _ in range(4):
            np.testing.assert_array_equal(
                ref, fleet.solve("m0", b, timeout=120))
    finally:
        fleet.close()


def test_deploy_during_traffic_drops_nothing(bundles, tmp_path):
    paths, mats = bundles
    a = mats["m0"]
    good2 = str(tmp_path / "m0_v3")
    save_lu(_factor(a), good2)
    fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=2, kind="thread")
    stop = threading.Event()
    outcomes = []
    lock = threading.Lock()
    b = a.matvec(np.ones(a.n_rows))

    def client():
        while not stop.is_set():
            try:
                fleet.solve("m0", b, timeout=120)
                tag = "ok"
            except Exception as e:        # noqa: BLE001 — tallied
                tag = type(e).__name__
            with lock:
                outcomes.append(tag)

    th = threading.Thread(target=client)
    th.start()
    try:
        time.sleep(0.05)
        fleet.deploy(good2)
        time.sleep(0.05)
    finally:
        stop.set()
        th.join(30)
        fleet.close()
    assert outcomes and set(outcomes) == {"ok"}, outcomes


# ---------------------------------------------------------------------------
# process replicas (the real kill -9 domain)
# ---------------------------------------------------------------------------

def test_process_replicas_kill9_zero_loss(bundles, monkeypatch):
    """Subprocess replicas behind the same interface: chaos SIGKILLs
    one replica process mid-stream (a REAL kill -9) and every accepted
    ticket is still delivered, bitwise-identical to the thread fleet's
    answers for the same stream."""
    paths, mats = bundles
    two = {k: paths[k] for k in ("m0", "m1")}
    ref, st0 = _run_stream(two, mats, n_replicas=2, n_tickets=8,
                           monkeypatch=monkeypatch)

    monkeypatch.setenv("SLU_TPU_CHAOS", "kill_replica=1@batch=1")
    fleet = FleetRouter(two, n_replicas=2, kind="process")
    try:
        tickets = _mixed_stream(fleet, mats, n_tickets=8,
                                keys=("m0", "m1"))
        got = [t.result(300) for t in tickets]
        st = fleet.stats()
        assert st["failovers"] >= 1 and st["errors"] == 0
        assert st["delivered"] == 8
        assert 1 in st["replicas_failed"]
        for i, (r, g) in enumerate(zip(ref, got)):
            assert np.array_equal(r, g), f"ticket {i} drifted"
    finally:
        fleet.close()
        monkeypatch.delenv("SLU_TPU_CHAOS", raising=False)


# ---------------------------------------------------------------------------
# evidence: metrics + postmortem
# ---------------------------------------------------------------------------

def test_fleet_metrics_series(bundles, monkeypatch):
    from superlu_dist_tpu.obs import metrics as metrics_mod
    paths, mats = bundles
    m = metrics_mod.Metrics()
    prev = metrics_mod.install(m)
    monkeypatch.setenv("SLU_TPU_CHAOS", "kill_replica=0@batch=1")
    try:
        fleet = FleetRouter({"m0": paths["m0"]}, n_replicas=2,
                            kind="thread")
        b = mats["m0"].matvec(np.ones(mats["m0"].n_rows))
        tickets = [fleet.submit("m0", b) for _ in range(6)]
        for t in tickets:
            t.result(120)
        fleet.close()
        snap = m.snapshot()
        c, g, h = (snap["counters"], snap["gauges"],
                   snap["histograms"])
        assert c["slu_fleet_requests_total"] == 6.0
        assert c["slu_fleet_columns_total"] == 6.0
        assert c["slu_fleet_failovers_total"] >= 1.0
        assert c["slu_fleet_reroutes_total"] >= 1.0
        assert "slu_fleet_replicas_healthy" in g
        assert "slu_fleet_route_seconds" in h
    finally:
        metrics_mod.install(prev)


def test_replica_failure_postmortem(bundles, monkeypatch, tmp_path):
    """The failover's ReplicaFailureError dumps a flight-recorder
    postmortem naming the dead replica and the re-routed ticket set."""
    from superlu_dist_tpu.obs import flightrec
    monkeypatch.setenv("SLU_TPU_FLIGHTREC",
                       str(tmp_path / "fleet-%p.json"))
    flightrec._reset()
    try:
        err = ReplicaFailureError(3, [7, 9], cause="unit", pid=123,
                                  kind="process")
        assert err.replica == 3 and err.tickets == [7, 9]
        assert "3" in str(err) and "[7, 9]" in str(err)
        assert err.flightrec_dump and os.path.exists(err.flightrec_dump)
        import json
        doc = json.load(open(err.flightrec_dump))
        assert doc["reason"] == "ReplicaFailureError"
        assert "[7, 9]" in doc["detail"]
    finally:
        monkeypatch.delenv("SLU_TPU_FLIGHTREC")
        flightrec._reset()


def test_deploy_rollback_error_fields():
    err = DeployRollbackError("k", "/tmp/bundle", "canary", replica=1,
                              rolled_back=[0, 1], cause="berr gate")
    assert err.stage == "canary" and err.rolled_back == [0, 1]
    assert "rolled back" in str(err) and "berr gate" in str(err)
