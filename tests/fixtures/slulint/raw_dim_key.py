"""slulint fixture: SLU107 positive — an lru_cached jit factory keyed
on RAW (unbucketed) dimensions.

This is the exact pattern that produced the BENCH_r02 119-kernel
compile wall: every distinct batch length / index count mints a fresh
compiled program, so the kernel count grows with the matrix instead of
staying a closed bucket set.  The v1 lexical SLU105 tier does NOT flag
this (no env read, no closure) — SLU107 exists for it.
"""

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=None)
def _kern(batch, width):
    def step(x):
        return jnp.sum(x.reshape(batch, width), axis=1)

    return jax.jit(step)


def run(chunks):
    outs = []
    for x in chunks:
        # BAD: len(x) and x.shape[0] feed the cache key raw — one
        # compiled program per distinct chunk size
        fn = _kern(x.shape[0], len(x[0]))
        outs.append(fn(jnp.asarray(x)))
    return outs
