"""Maximum-product bipartite matching for static pivoting ("MC64 job=5").

Capability analog of dldperm_dist + the f2c'd HSL kernel mc64ad_dist
(SRC/dldperm_dist.c:95, SRC/mc64ad_dist.c:121), used for
RowPerm=LargeDiag_MC64: find a row permutation maximizing the product of
diagonal magnitudes, plus row/col scalings (from the LP duals) that make the
matched entries ±1 and all others ≤ 1 in magnitude.  This is a fresh
implementation of successive-shortest-augmenting-path matching (sparse
Hungarian/LAPJV with potentials) on costs c_ij = log(colmax_j / |a_ij|).

Like the reference (which runs MC64 serially on rank 0 and broadcasts,
pdgssvx.c:812-833), this runs on the host.
"""

from __future__ import annotations

import heapq

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSC, SparseCSR
from superlu_dist_tpu.utils.errors import SuperLUError


def maximum_product_matching(a, want_scalings: bool = True):
    """Return (row_order, r, c).

    ``row_order[j]`` is the original row to place at position j, so that
    ``A[row_order, :]`` has the matched (maximum-product) entries on its
    diagonal.  ``r``/``c`` are the MC64 job=5 scaling vectors: with
    B = diag(r) · A · diag(c), every matched entry of B is ±1 (or unit
    modulus, complex) and all entries have magnitude ≤ 1.
    """
    csc = a if isinstance(a, SparseCSC) else a.tocsc()
    n, m = csc.shape
    if n != m:
        raise SuperLUError("matching requires a square matrix")
    indptr, indices = csc.indptr, csc.indices
    absval = np.abs(csc.data).astype(np.float64)

    colmax = np.zeros(n)
    cols = np.repeat(np.arange(n), np.diff(indptr))
    np.maximum.at(colmax, cols, absval)
    if np.any(colmax == 0):
        raise SuperLUError("structurally singular: empty column")

    # native path (slu_host.cpp slu_mc64 — same algorithm, compiled)
    from superlu_dist_tpu import native
    if native.available():
        try:
            col_match, u_n, v_n = native.mc64(n, indptr, indices, absval)
        except ValueError as e:
            raise SuperLUError(f"structurally singular: {e}") from e
        if not want_scalings:
            return col_match, None, None
        return (col_match,) + _scalings_from_duals(u_n, v_n, colmax)

    # costs: c_k = log(colmax_j) - log|a_k| >= 0; explicit zeros excluded
    with np.errstate(divide="ignore"):
        cost = np.log(colmax[cols]) - np.log(absval)   # +inf for zeros

    INF = np.inf
    u = np.zeros(n)            # column duals
    v = np.zeros(n)            # row duals
    row_match = np.full(n, -1, dtype=np.int64)   # row -> col
    col_match = np.full(n, -1, dtype=np.int64)   # col -> row

    dist = np.empty(n)
    pred = np.empty(n, dtype=np.int64)
    done = np.empty(n, dtype=bool)

    for j0 in range(n):
        dist.fill(INF)
        pred.fill(-1)
        done.fill(False)
        tree_cols = [j0]
        d_col = {j0: 0.0}
        heap = []

        def relax(j, base):
            for k in range(indptr[j], indptr[j + 1]):
                if not np.isfinite(cost[k]):
                    continue
                i = indices[k]
                if done[i]:
                    continue
                nd = base + cost[k] - u[j] - v[i]
                if nd < dist[i] - 1e-30:
                    dist[i] = nd
                    pred[i] = j
                    heapq.heappush(heap, (nd, int(i)))

        relax(j0, 0.0)
        found = -1
        while heap:
            d, i = heapq.heappop(heap)
            if done[i] or d > dist[i]:
                continue
            done[i] = True
            if row_match[i] == -1:
                found = i
                break
            jnext = int(row_match[i])
            tree_cols.append(jnext)
            d_col[jnext] = d
            relax(jnext, d)
        if found == -1:
            raise SuperLUError("structurally singular: no perfect matching")
        mind = dist[found]
        # dual updates keep reduced costs >= 0 with matched edges tight
        scanned = done & (dist <= mind)
        v[scanned] += dist[scanned] - mind
        for j in tree_cols:
            u[j] += mind - d_col[j]
        # augment along the alternating path
        i = found
        while i != -1:
            j = int(pred[i])
            inext = col_match[j]
            row_match[i] = j
            col_match[j] = i
            i = int(inext)
            if j == j0:
                break

    row_order = col_match.copy()      # position j <- original row matched to col j
    if not want_scalings:
        return row_order, None, None
    return (row_order,) + _scalings_from_duals(u, v, colmax)


def approximate_weight_matching(a) -> np.ndarray:
    """AWPM row permutation — the CombBLAS HWPM analog
    (SRC/d_c2cpp_GetHWPM.cpp, dHWPM_CombBLAS.hpp:40): a cheap approximate
    maximum-weight perfect matching (greedy on weight-sorted edges +
    max-cardinality augmentation), permutation only, no scalings.

    Falls back to the exact MC64 matching (without scalings) when the
    native library is unavailable — exact is a valid "approximation".
    """
    csc = a if isinstance(a, SparseCSC) else a.tocsc()
    n, m = csc.shape
    if n != m:
        raise SuperLUError("matching requires a square matrix")
    from superlu_dist_tpu import native
    if native.available():
        try:
            return native.awpm(n, csc.indptr, csc.indices, np.abs(csc.data))
        except ValueError as e:
            raise SuperLUError(f"structurally singular: {e}") from e
    row_order, _, _ = maximum_product_matching(csc, want_scalings=False)
    return row_order


def _scalings_from_duals(u: np.ndarray, v: np.ndarray, colmax: np.ndarray):
    """r_i = exp(v_i), c_j = exp(u_j)/colmax_j => matched |r_i a_ij c_j| = 1
    (the MC64 job=5 scaling recovery, shared by the native and Python
    matching paths)."""
    cap = 700.0                       # keep exp() finite
    r = np.exp(np.clip(v, -cap, cap))
    c = np.exp(np.clip(u - np.log(colmax), -cap, cap))
    return r, c
