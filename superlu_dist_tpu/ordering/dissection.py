"""Nested-dissection fill-reducing orderings.

Capability analog of the reference's METIS_AT_PLUS_A / ParMETIS orderings
(SRC/get_perm_c.c:90, get_perm_c_parmetis.c:255).  Two implementations:

* :func:`geometric_nd` — exact recursive coordinate bisection for matrices
  that carry a ``grid_shape`` attribute (the model-problem gallery).  For a
  d-dimensional grid this gives the optimal O(n^{ (d+? )}) fill growth the
  reference obtains from ParMETIS on mesh problems (SURVEY.md §5).
* :func:`bfs_nd` — general-graph nested dissection using BFS level-set
  separators from a pseudo-peripheral vertex (numpy-vectorized frontiers),
  recursing until small leaves.

Both return an elimination *order* (order[k] = old index of the k-th pivot).
"""

from __future__ import annotations

import numpy as np


def geometric_nd(grid_shape) -> np.ndarray:
    """Recursive coordinate bisection on a structured grid."""
    dims = tuple(int(d) for d in grid_shape)
    n = int(np.prod(dims))
    strides = np.array([int(np.prod(dims[i + 1:])) for i in range(len(dims))],
                       dtype=np.int64)
    out = np.empty(n, dtype=np.int64)
    pos = 0
    # stack of boxes: (lo tuple, hi tuple) half-open
    stack = [(tuple(0 for _ in dims), dims)]
    emit_stack = []   # (box, kind) processed iteratively: we emit via explicit
                      # ordering: children first then separator, so run a
                      # post-order traversal with an explicit output list.

    def box_indices(lo, hi):
        slices = [np.arange(l, h) for l, h in zip(lo, hi)]
        grids = np.meshgrid(*slices, indexing="ij")
        idx = np.zeros_like(grids[0])
        for g, s in zip(grids, strides):
            idx = idx + g * s
        return idx.ravel()

    def rec(lo, hi):
        nonlocal pos
        sizes = [h - l for l, h in zip(lo, hi)]
        if max(sizes) <= 3:
            idx = box_indices(lo, hi)
            out[pos:pos + len(idx)] = idx
            pos += len(idx)
            return
        ax = int(np.argmax(sizes))
        mid = (lo[ax] + hi[ax]) // 2
        lo1, hi1 = list(lo), list(hi)
        hi1[ax] = mid
        lo2, hi2 = list(lo), list(hi)
        lo2[ax] = mid + 1
        rec(tuple(lo1), tuple(hi1))
        rec(tuple(lo2), tuple(hi2))
        sep_lo, sep_hi = list(lo), list(hi)
        sep_lo[ax], sep_hi[ax] = mid, mid + 1
        idx = box_indices(tuple(sep_lo), tuple(sep_hi))
        out[pos:pos + len(idx)] = idx
        pos += len(idx)

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, 10000))
    try:
        rec(tuple(0 for _ in dims), dims)
    finally:
        sys.setrecursionlimit(old)
    assert pos == n
    return out


def _bfs_levels(indptr, indices, start, mask, comp_nodes):
    """BFS level sets within the masked subgraph; returns (levels dict list)."""
    level = {}
    frontier = [start]
    level_of = {start: 0}
    levels = [[start]]
    seen = {start}
    while frontier:
        nxt = []
        for u in frontier:
            for v in indices[indptr[u]:indptr[u + 1]]:
                v = int(v)
                if mask[v] and v not in seen:
                    seen.add(v)
                    nxt.append(v)
        if nxt:
            levels.append(nxt)
        frontier = nxt
    return levels, seen


def bfs_nd(n, indptr, indices, leaf_size: int = 32) -> np.ndarray:
    """General-graph nested dissection via BFS level-set separators."""
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    out = np.empty(n, dtype=np.int64)
    pos = 0
    mask = np.ones(n, dtype=bool)

    def emit(nodes):
        nonlocal pos
        out[pos:pos + len(nodes)] = nodes
        pos += len(nodes)

    stack = [np.arange(n, dtype=np.int64)]
    # Work items: ('part', nodes) to dissect, ('emit', nodes) to output.
    work = [("part", np.arange(n, dtype=np.int64))]
    while work:
        kind, nodes = work.pop()
        if kind == "emit":
            emit(nodes)
            continue
        if len(nodes) <= leaf_size:
            emit(nodes)
            continue
        sub = np.zeros(n, dtype=bool)
        sub[nodes] = True
        # find a connected component and a pseudo-peripheral vertex
        start = int(nodes[0])
        levels, seen = _bfs_levels(indptr, indices, start, sub, nodes)
        if len(seen) < len(nodes):
            # disconnected: split off this component, requeue the rest
            comp = np.fromiter(seen, dtype=np.int64)
            rest = nodes[~np.isin(nodes, comp)]
            work.append(("part", rest))
            work.append(("part", comp))
            continue
        # second BFS from the farthest vertex for a better diameter estimate
        far = levels[-1][0]
        levels, _ = _bfs_levels(indptr, indices, int(far), sub, nodes)
        if len(levels) <= 2:
            emit(nodes)      # tightly-coupled clique-ish blob: no separator
            continue
        sizes = np.array([len(l) for l in levels])
        half = np.searchsorted(np.cumsum(sizes), len(nodes) / 2.0)
        half = int(np.clip(half, 1, len(levels) - 2))
        sep = np.array(levels[half], dtype=np.int64)
        a_part = np.concatenate([np.array(l, dtype=np.int64)
                                 for l in levels[:half]])
        b_part = (np.concatenate([np.array(l, dtype=np.int64)
                                  for l in levels[half + 1:]])
                  if half + 1 < len(levels) else np.empty(0, dtype=np.int64))
        # order: A, B, then separator last (post-order emit via stack: push
        # reversed)
        work.append(("emit", sep))
        if len(b_part):
            work.append(("part", b_part))
        work.append(("part", a_part))
    assert pos == n, (pos, n)
    return out
