"""Multi-process expert driver (pdgssvx-with-NR_loc-input analog):
block-row distributed A and b in four real processes, tree-collective
gather to the factoring root, distributed refinement back out."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _worker(name, n_ranks, rank, part, b_loc, q, options=None):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx
    from superlu_dist_tpu.utils.options import Options
    with TreeComm(name, n_ranks, rank, max_len=2048, create=False) as tc:
        x, info = pgssvx(tc, options if options is not None else Options(),
                         part, b_loc)
        q.put((rank, info, x))


def test_pgssvx_four_processes():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx

    a = convection_diffusion_2d(11)
    n = a.n_rows
    xtrue = np.random.default_rng(2).standard_normal(n)
    b = a.matvec(xtrue)

    nranks = 4
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]

    name = f"/slu_pgssvx_{os.getpid()}"
    owner = TreeComm(name, nranks, 0, max_len=2048, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, nranks, r, parts[r],
                                   b_blocks[r], q))
                 for r in range(1, nranks)]
        for p in procs:
            p.start()
        x, info = pgssvx(owner, slu.Options(), parts[0], b_blocks[0])
        assert info == 0
        others = [q.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=300)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)

    # serial reference through the plain driver
    x_ref, _, _, info_ref = slu.gssvx(slu.Options(), a, b)
    assert info_ref == 0
    resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    assert resid < 1e-13, resid
    np.testing.assert_allclose(x, x_ref, rtol=1e-9, atol=1e-11)
    for rank, info_r, xr in others:
        assert info_r == 0
        np.testing.assert_allclose(xr, x, rtol=0, atol=1e-12)


def _run_pgssvx_case(make_matrix, make_b, options, nranks=2, check=None):
    """Drive pgssvx across nranks fork-processes and return rank 0's x."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgssvx import pgssvx

    a = make_matrix()
    b = make_b(a)
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]
    name = f"/slu_pgx_{os.getpid()}_{abs(hash(str(options))) % 10000}"
    owner = TreeComm(name, nranks, 0, max_len=2048, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, nranks, r, parts[r], b_blocks[r],
                                   q), kwargs={"options": options})
                 for r in range(1, nranks)]
        for p in procs:
            p.start()
        x, info = pgssvx(owner, options, parts[0], b_blocks[0])
        assert info == 0
        others = [q.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=300)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    for rank, info_r, xr in others:
        assert info_r == 0
        np.testing.assert_allclose(xr, x, rtol=0, atol=1e-12)
    if check is not None:
        check(a, b, x)
    return a, b, x


def test_pgssvx_multi_rhs():
    """nrhs > 1 round-trips through gather, factor, and per-RHS
    refinement (the reference's pdgssvx nrhs loop, pdgsrfs.c:205)."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d

    rng = np.random.default_rng(5)

    def chk(a, b, x):
        assert x.shape == b.shape == (a.n_rows, 3)
        for j in range(3):
            r = np.linalg.norm(b[:, j] - a.matvec(x[:, j]))
            assert r / np.linalg.norm(b[:, j]) < 1e-12

    _run_pgssvx_case(lambda: convection_diffusion_2d(9),
                     lambda a: rng.standard_normal((a.n_rows, 3)),
                     slu.Options(), check=chk)


def test_pgssvx_trans():
    """options.trans solves Aᵀ·x = b collectively (reference trans_t)."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import convection_diffusion_2d
    from superlu_dist_tpu.utils.options import Trans

    rng = np.random.default_rng(6)

    def chk(a, b, x):
        at = a.transpose()
        r = np.linalg.norm(b - at.matvec(x)) / np.linalg.norm(b)
        assert r < 1e-12, r

    _run_pgssvx_case(lambda: convection_diffusion_2d(9),
                     lambda a: rng.standard_normal(a.n_rows),
                     slu.Options(trans=Trans.TRANS), check=chk)


def test_pgssvx_complex():
    """Complex A/b (the pzgssvx twin): payloads ride the f64 tree as
    re/im passes; refinement stays componentwise on magnitudes."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import helmholtz_2d

    rng = np.random.default_rng(7)

    def chk(a, b, x):
        assert np.iscomplexobj(x)
        r = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
        assert r < 1e-12, r

    _run_pgssvx_case(lambda: helmholtz_2d(9),
                     lambda a: (rng.standard_normal(a.n_rows)
                                + 1j * rng.standard_normal(a.n_rows)),
                     slu.Options(), check=chk)


def test_pgssvx_complex_conj_multi_rhs():
    """The axes composed: complex A, Aᴴ solve (CONJ), nrhs=2 — the
    pzgssvx trans_t surface in one collective call."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import helmholtz_2d
    from superlu_dist_tpu.utils.options import Trans

    rng = np.random.default_rng(8)

    def chk(a, b, x):
        # residual vs Aᴴ: build it from the CSR triple directly
        import scipy.sparse as sp
        A = sp.csr_matrix((a.data, a.indices, a.indptr),
                          shape=(a.n_rows, a.n_cols))
        AH = A.conj().T
        for j in range(2):
            r = np.linalg.norm(b[:, j] - AH @ x[:, j]) \
                / np.linalg.norm(b[:, j])
            assert r < 1e-12, r

    _run_pgssvx_case(lambda: helmholtz_2d(8),
                     lambda a: (rng.standard_normal((a.n_rows, 2))
                                + 1j * rng.standard_normal((a.n_rows, 2))),
                     slu.Options(trans=Trans.CONJ), check=chk)


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
