"""Concurrency-correctness suite (docs/ANALYSIS.md SLU108-SLU110).

Static tier: per-rule true-positive + clean-negative fixtures under
tests/fixtures/slulint/, interprocedural resolution cases, and the
whole-tree-scans-clean acceptance.  Runtime tier: the SLU109 lock-order
verifier (utils/lockwatch.py, ``SLU_TPU_VERIFY_LOCKS=1``) — provoked
two-thread inversion raising :class:`LockOrderError` with both sites
named, zero state on the off path, the hold-seconds histogram, and a
full ``SolveServer`` serve cycle running clean under it.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from superlu_dist_tpu.analysis import analyze_paths, analyze_source
from superlu_dist_tpu.analysis import default_rules
from superlu_dist_tpu.utils import lockwatch
from superlu_dist_tpu.utils.errors import LockOrderError

pytestmark = pytest.mark.locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures", "slulint")


def fixture_rules(name):
    return [f.rule for f in analyze_paths([os.path.join(FIXDIR, name)])]


# --------------------------------------------------------------------------
# SLU108 — unguarded shared-mutable access
# --------------------------------------------------------------------------

def test_slu108_fixture_pair():
    fs = analyze_paths([os.path.join(FIXDIR, "unguarded_shared.py")])
    assert [f.rule for f in fs] == ["SLU108"]
    assert "self._count" in fs[0].message
    assert "background thread" in fs[0].message
    assert "_loop" in fs[0].message          # the thread-side witness
    assert fixture_rules("guarded_shared.py") == []


SLU108_TRANSITIVE = """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._t.start()

    def _loop(self):
        self._step()

    def _step(self):
        self._n += 1          # unguarded write, two hops from target

    def peek(self):
        with self._lock:
            return self._n

    def close(self):
        self._t.join(1.0)
"""


def test_slu108_thread_side_resolved_through_callgraph():
    """The write sits two call-graph hops below the Thread target; the
    rule still attributes it to the thread side (and flags it, since
    the public peek() proves the attribute is shared)."""
    fs = analyze_source(SLU108_TRANSITIVE, "fixture.py", default_rules())
    slu108 = [f for f in fs if f.rule == "SLU108"]
    assert len(slu108) == 1
    assert "thread-side write" in slu108[0].message


SLU108_LOCKED_HELPER = """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self._n += 1          # every call site holds the lock

    def peek(self):
        with self._lock:
            return self._n

    def close(self):
        self._t.join(1.0)
"""


def test_slu108_lock_context_helper_counts_as_guarded():
    """A helper whose every in-class call site is under the guard is
    effectively guarded (the _take_batch caller-holds-the-lock idiom)."""
    fs = analyze_source(SLU108_LOCKED_HELPER, "fixture.py",
                        default_rules())
    assert [f.rule for f in fs if f.rule == "SLU108"] == []


# --------------------------------------------------------------------------
# SLU109 — lock order + hold discipline
# --------------------------------------------------------------------------

def test_slu109_cycle_fixture_names_both_sites():
    fs = analyze_paths([os.path.join(FIXDIR, "lock_cycle.py")])
    assert [f.rule for f in fs] == ["SLU109", "SLU109"]
    msgs = " ".join(f.message for f in fs)
    assert "inversion" in msgs and "deadlock" in msgs
    # each finding names the OTHER site of the cycle
    assert "lock_cycle.py:16" in fs[1].message \
        or "lock_cycle.py:21" in fs[0].message


def test_slu109_blocking_hold_fixture():
    fs = analyze_paths([os.path.join(FIXDIR, "blocking_hold.py")])
    assert [f.rule for f in fs] == ["SLU109", "SLU109"]
    msgs = " ".join(f.message for f in fs)
    assert "file I/O" in msgs and "bcast_any" in msgs
    assert fixture_rules("lock_discipline_clean.py") == []


SLU109_VIA_CALL = """
import threading

_A = threading.Lock()
_B = threading.Lock()

def inner():
    with _B:
        return 1

def outer():
    with _A:
        return inner()

def inverse():
    with _B:
        with _A:
            return 2
"""


def test_slu109_edge_through_call_graph():
    """The A->B edge exists only through outer()'s CALL to inner();
    the inverse() nesting still closes the cycle."""
    fs = analyze_source(SLU109_VIA_CALL, "fixture.py", default_rules())
    slu109 = [f for f in fs if f.rule == "SLU109"]
    assert len(slu109) == 2
    assert any("via" in f.message or "call to" in f.message
               for f in slu109)


SLU109_SELF_NEST = """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def oops(self):
        with self._lock:
            with self._lock:
                return 1
"""


def test_slu109_self_reacquisition():
    fs = analyze_source(SLU109_SELF_NEST, "fixture.py", default_rules())
    assert [f.rule for f in fs] == ["SLU109"]
    assert "self-deadlock" in fs[0].message


# --------------------------------------------------------------------------
# SLU110 — thread lifecycle
# --------------------------------------------------------------------------

def test_slu110_fixture_pair():
    fs = analyze_paths([os.path.join(FIXDIR, "thread_lifecycle.py")])
    assert [f.rule for f in fs] == ["SLU110"] * 3
    msgs = " ".join(f.message for f in fs)
    assert "never join()ed" in msgs
    assert "before dependent attribute" in msgs and "_interval" in msgs
    assert "never wait()ed" in msgs and "_unused" in msgs
    assert fixture_rules("thread_lifecycle_clean.py") == []


# --------------------------------------------------------------------------
# whole-tree acceptance
# --------------------------------------------------------------------------

def test_concurrency_rules_scan_tree_clean():
    """Acceptance: SLU108-SLU110 over the default scope scan clean
    (every true positive fixed or justified inline in this PR) and
    finish inside the CI budget."""
    r = subprocess.run(
        [sys.executable, "-m", "superlu_dist_tpu.analysis",
         "--no-baseline", "--rules", "SLU108,SLU109,SLU110"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


# --------------------------------------------------------------------------
# runtime verifier (SLU_TPU_VERIFY_LOCKS=1)
# --------------------------------------------------------------------------

@pytest.fixture
def verify_locks(monkeypatch):
    monkeypatch.setenv("SLU_TPU_VERIFY_LOCKS", "1")
    lockwatch._reset()
    yield lockwatch
    monkeypatch.delenv("SLU_TPU_VERIFY_LOCKS", raising=False)
    lockwatch._reset()


def test_verifier_off_path_allocates_no_state(monkeypatch):
    monkeypatch.delenv("SLU_TPU_VERIFY_LOCKS", raising=False)
    lockwatch._reset()
    lock = lockwatch.make_lock("off.test")
    assert type(lock) is type(threading.Lock())      # a PLAIN lock
    cond = lockwatch.make_condition("off.cond")
    assert type(cond) is threading.Condition
    assert lockwatch._WATCH is None                  # no watch, no graph
    assert lockwatch.order_graph() == {}
    lockwatch._reset()


def test_provoked_two_thread_inversion_names_both_sites(verify_locks):
    """The acceptance inversion: worker establishes A->B, the main
    thread then tries B->A — LockOrderError raises BEFORE blocking,
    naming both acquisition sites."""
    a = lockwatch.make_lock("inv.A")
    b = lockwatch.make_lock("inv.B")

    def establish():
        with a:
            with b:             # records the A->B edge
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join(10.0)
    assert lockwatch.order_graph().get("inv.A") == ["inv.B"]

    with pytest.raises(LockOrderError) as ei:
        with b:
            with a:             # the inversion — raises, never blocks
                pass
    err = ei.value
    assert err.outer == "inv.B" and err.inner == "inv.A"
    # BOTH call sites named: this file for the inverting acquisition,
    # and the recorded witness of the worker's A->B edge
    assert "test_locks.py" in err.site
    assert "test_locks.py" in err.inverse_site
    assert err.site != err.inverse_site
    assert "SLU109" in str(err)


def test_verifier_hold_seconds_histogram(verify_locks):
    from superlu_dist_tpu.obs import metrics as M
    m = M.Metrics()
    prev = M.install(m)
    try:
        with lockwatch.make_lock("hist.L"):
            pass
        snap = m.snapshot()
        assert 'slu_lock_hold_seconds{lock="hist.L"}' in snap["histograms"]
    finally:
        M.install(prev)


def test_condition_shares_lock_identity(verify_locks):
    """make_condition over a make_lock: waits/notifies run through ONE
    instrumented identity (the Condition(self._lock) idiom) without
    phantom edges or errors."""
    lock = lockwatch.make_lock("cond.L")
    cond = lockwatch.make_condition("cond.C", lock)
    hits = []

    def waiter():
        with cond:
            hits.append(cond.wait(5.0))

    t = threading.Thread(target=waiter)
    t.start()
    import time
    for _ in range(100):
        with cond:
            if hits:
                break
            cond.notify_all()
        time.sleep(0.01)
    with cond:
        cond.notify_all()
    t.join(10.0)
    assert not t.is_alive()


# --------------------------------------------------------------------------
# the serve tier runs clean under the verifier (acceptance)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def factored():
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.utils.options import IterRefine, Options
    a = poisson2d(10)
    rng = np.random.default_rng(0)
    b = a.matvec(rng.standard_normal(a.n_rows))
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, b)
    assert info == 0
    return a, lu


def test_solve_server_clean_under_lock_verifier(verify_locks, factored):
    """A full serve cycle — backlog, dispatch, scrub, swap, close —
    with every server lock instrumented: no LockOrderError, results
    correct, and the server locks visible in the order graph's node
    set (proof the instrumentation was live, not bypassed)."""
    from superlu_dist_tpu.serve.server import SolveServer
    a, lu = factored
    rng = np.random.default_rng(3)
    srv = SolveServer(lu, max_wait_s=0.01, start=False)
    assert type(srv._lock).__name__ == "InstrumentedLock"
    rhss = [a.matvec(rng.standard_normal(a.n_rows)) for _ in range(4)]
    tickets = [srv.submit(r) for r in rhss]
    srv.start()
    for t, r in zip(tickets, rhss):
        got = t.result(60)
        res = np.linalg.norm(r - a.matvec(got)) / np.linalg.norm(r)
        assert res < 1e-8, res
    srv.scrub_now()
    srv.swap(lu)
    assert srv.solve(rhss[0], timeout=60).shape == (a.n_rows,)
    srv.close()
    st = srv.stats()
    assert st["errors"] == 0 and st["requests"] == 5


TREECOMM_CHILD = r"""
import json, os
import numpy as np
from superlu_dist_tpu import native
if not native.available():
    print(json.dumps({"skip": True}))
    raise SystemExit(0)
from superlu_dist_tpu.parallel import treecomm
from superlu_dist_tpu.utils import lockwatch

name = f"/slu_lockgate_{os.getpid()}"
with treecomm.TreeComm(name, 1, 0, max_len=64, create=True) as tc:
    payload = np.arange(16.0)
    ok = bool((tc.allreduce_sum_any(payload.copy()) == payload).all())
print(json.dumps({"ok": ok, "watch": lockwatch._WATCH is not None}))
"""


def test_treecomm_clean_under_lock_verifier():
    """The collective path (native build lock, comm telemetry) runs
    clean with lock verification armed — the per-suite acceptance in
    miniature (the multi-rank suites inherit the env the same way)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLU_TPU_VERIFY_LOCKS="1")
    r = subprocess.run([sys.executable, "-c", TREECOMM_CHILD], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    import json
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    if doc.get("skip"):
        pytest.skip("native library unavailable")
    assert doc["ok"] and doc["watch"]
