from superlu_dist_tpu.io.readers import (
    read_harwell_boeing, read_rutherford_boeing, read_matrix_market,
    read_triples, read_binary, write_matrix_market, write_binary, read_matrix,
)
