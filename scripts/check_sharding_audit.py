#!/usr/bin/env python
"""Sharding-audit gate (slulint v6): the tree is clean under the
sharding/memory rules and every program the REAL executors build passes
the runtime sharding audit inside a generous memory budget.

Phase A — whole-tree source scan: SLU119 (implicit replication — catalog
stub), SLU120 (mesh/spec hygiene against utils/meshreg.py), SLU121
(peak-memory — catalog stub) and SLU122 (dispatch-loop cross-mesh
transfers) over the default scan scope via the slulint CLI — any
finding fails the gate (the baseline stays empty).

Phase B — runtime twin coverage: ``SLU_TPU_VERIFY_SHARDING=1`` plus a
generous ``SLU_TPU_MEM_BUDGET_BYTES`` (1 GiB) over the gate gallery
(poisson2d + hilbert) through all three factor executors and the device
solve sweeps (fused and streamed, plain and transpose): every submitted
program is traced and priced by ``audit_resharding``/
``audit_peak_memory`` with ZERO findings, the census ``#sharding``
notes cover 100% of the audited programs, every audited program carries
a nonzero ``peak_bytes_est``, and — where
``compiled.memory_analysis()`` is available — the mega executor's
static estimates agree with XLA's own temp+arg+output total within 2x.

Phase C — budget enforcement: a fresh subprocess with a tiny budget
proves a mega-bucket factorization raises ``MemoryBudgetError`` BEFORE
any program runs, naming the offending bucket RUNG (the ``P`` pool
component of the census label) and the peak/budget byte verdict.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (shared contract:
diagnostics on stdout/stderr, non-zero on any regression, hard
timeout).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATE_BUDGET = 1 << 30           # 1 GiB: generous for the gate gallery

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SLU_TPU_VERIFY_SHARDING"] = "1"
os.environ["SLU_TPU_MEM_BUDGET_BYTES"] = str(GATE_BUDGET)

import numpy as np  # noqa: E402


def phase_a() -> None:
    cmd = [sys.executable, "-m", "superlu_dist_tpu.analysis",
           "--rules", "SLU119,SLU120,SLU121,SLU122", "--no-baseline"]
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    assert proc.returncode == 0, \
        "whole-tree SLU119-SLU122 scan found new sharding findings"
    print("[sharding-audit] phase A: tree clean under SLU119-SLU122")


def _analyzed(a):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    return sf, sym.data[sf.value_perm], a.norm_max()


def check(name, a) -> int:
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.solve.device import DeviceSolver

    sf, vals, anorm = _analyzed(a)
    plan = build_plan(sf)
    rng = np.random.default_rng(7)
    rhs = rng.standard_normal((plan.n, 5))
    for ex in ("fused", "stream", "mega"):
        fact = numeric_factorize(plan, vals, anorm, executor=ex)
        if ex == "stream":
            for fused in (True, False):
                ds = DeviceSolver(fact, fused=fused)
                ds.solve(rhs)
                ds.solve_trans(rhs)
    from superlu_dist_tpu.utils import programaudit
    aud = programaudit.get_sharding_auditor()
    assert aud is not None, \
        "SLU_TPU_VERIFY_SHARDING=1 allocated no auditor"
    assert aud.budget_bytes == GATE_BUDGET, aud.budget_bytes
    assert aud.findings == [], aud.findings
    assert all(s["peak_bytes_est"] > 0 for s in aud.audited.values()), \
        "an audited program carries no peak estimate"
    print(f"[sharding-audit] {name}: {len(aud.audited)} program(s) "
          "audited clean inside the budget")
    return len(aud.audited)


def check_mega_vs_xla() -> None:
    """The SLU121 estimates for the mega bucket programs agree with
    XLA's own memory_analysis within 2x, where the API exists."""
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS

    a = poisson2d(12)
    sf, _, _ = _analyzed(a)
    ex = MegaExecutor(build_plan(sf), "float64")
    ex.prebake()
    peaks = {}
    with COMPILE_STATS._lock:
        for (site, k), v in COMPILE_STATS._audits.items():
            if site == "mega._kernel" and k.endswith("#sharding"):
                peaks[k[:-len("#sharding")]] = v.get("peak_bytes_est", 0)
    assert peaks, "mega prebake produced no #sharding audit notes"
    compared = 0
    for (key, _), compiled in ex._mega_fns.items():
        label = ex._census_label(key)
        est = peaks.get(label, 0)
        assert est > 0, f"no peak estimate for mega bucket {label}"
        ma = getattr(compiled, "memory_analysis", lambda: None)()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            continue
        xla = (int(ma.temp_size_in_bytes)
               + int(ma.argument_size_in_bytes)
               + int(getattr(ma, "output_size_in_bytes", 0)))
        if xla <= 0:
            continue
        assert xla / 2 <= est <= xla * 2, \
            (f"mega bucket {label}: static peak {est} vs XLA {xla} "
             "outside the 2x acceptance band")
        compared += 1
    if compared:
        print(f"[sharding-audit] mega vs XLA: {compared} bucket "
              "program(s) within 2x of memory_analysis")
    else:
        print("[sharding-audit] mega vs XLA: memory_analysis "
              "unavailable — estimates present, agreement unchecked")


# the phase-C child: a tiny budget must reject the mega bucket programs
# at AOT-stage time, naming the pool rung
_BUDGET_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["SLU_REPO"])
import numpy as np
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.utils.errors import MemoryBudgetError
from superlu_dist_tpu.utils.options import Options

a = poisson2d(8)
sym = symmetrize_pattern(a)
sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
plan = build_plan(sf)
try:
    numeric_factorize(plan, sym.data[sf.value_perm], a.norm_max(),
                      executor="mega")
    out = {"raised": None}
except MemoryBudgetError as e:
    out = {"raised": "MemoryBudgetError", "site": e.site,
           "program": e.program, "peak": e.peak_bytes,
           "budget": e.budget_bytes, "rules": e.rules}
print(json.dumps(out))
"""


def phase_c() -> None:
    env = dict(os.environ, JAX_PLATFORMS="cpu", SLU_REPO=REPO,
               SLU_TPU_MEM_BUDGET_BYTES="4096")
    env.pop("SLU_TPU_VERIFY_SHARDING", None)   # the budget alone implies
    r = subprocess.run([sys.executable, "-c", _BUDGET_CHILD], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["raised"] == "MemoryBudgetError", out
    assert out["site"] == "mega._kernel", out
    assert " P" in out["program"], \
        f"budget error does not name the bucket rung: {out['program']}"
    assert out["peak"] > out["budget"] == 4096, out
    assert out["rules"] == ["SLU121"], out
    print(f"[sharding-audit] phase C: MemoryBudgetError named bucket "
          f"{out['program']!r} ({out['peak']} B over the "
          f"{out['budget']} B budget) before any program ran")


def main():
    phase_a()

    import jax
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.models.gallery import hilbert, poisson2d

    total = 0
    total = max(total, check("poisson2d nx=12", poisson2d(12)))
    total = max(total, check("hilbert n=48", hilbert(48)))
    check_mega_vs_xla()

    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils import programaudit
    aud = programaudit.get_sharding_auditor()
    blk = COMPILE_STATS.audit_block()
    assert blk["programs_sharding_audited"] == len(aud.audited) > 0, \
        f"census #sharding notes disagree: {blk} vs {len(aud.audited)}"
    assert blk["peak_bytes_est"] > 0, blk
    print(f"[sharding-audit] OK: {blk['programs_sharding_audited']} "
          f"programs sharding-audited, 0 findings, 100% coverage, "
          f"worst peak {blk['peak_bytes_est']} B inside the "
          f"{GATE_BUDGET} B budget")

    phase_c()


if __name__ == "__main__":
    main()
