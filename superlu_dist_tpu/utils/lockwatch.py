"""Runtime lock-order verifier — slulint SLU109's dynamic twin.

Static SLU109 (analysis/rules_lockorder.py) proves ordering over the
acquisitions it can resolve; data-dependent paths (callbacks, swapped
handles, test harnesses) need a runtime check — the same division of
labor as SLU101/SLU106 for collectives.  ``SLU_TPU_VERIFY_LOCKS=1``
swaps every lock built through :func:`make_lock` /
:func:`make_condition` for an instrumented wrapper that records
per-thread acquisition stacks into one process-global order graph:
edge ``A -> B`` the first time B is acquired while A is held, with the
acquiring call site as the witness.  The check runs BEFORE blocking on
the inner lock, so the first inversion raises a structured
:class:`~superlu_dist_tpu.utils.errors.LockOrderError` naming both call
sites — a would-be deadlock converted into a diagnosis (with its
flight-recorder postmortem already dumped at construction), instead of
two threads frozen forever.

Observability: each release feeds a ``slu_lock_hold_seconds`` histogram
(labeled by lock name) into the metrics registry when it is enabled —
the hold-time distribution the SLU109 hold-discipline rule polices
statically.

Disabled path (the SLU106 discipline): with the knob unset,
:func:`make_lock` returns a PLAIN ``threading.Lock`` — no wrapper, no
graph, no module state beyond the latched flag; ``_WATCH`` stays None.
``scripts/check_verify_overhead.py`` enforces this in CI.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_enabled = None          # latched on first use; _reset() re-reads
_WATCH = None            # the single _Watch when enabled, else None


def verify_locks_enabled() -> bool:
    global _enabled, _WATCH
    if _enabled is None:
        from superlu_dist_tpu.utils.options import env_flag
        _enabled = bool(env_flag("SLU_TPU_VERIFY_LOCKS"))
        if _enabled and _WATCH is None:
            _WATCH = _Watch()
    return _enabled


def _reset() -> None:
    """Re-read ``SLU_TPU_VERIFY_LOCKS`` on next use (test hygiene).
    Locks built before the reset keep their old behavior — rebuild the
    producers, exactly like metrics.install()."""
    global _enabled, _WATCH
    _enabled = None
    _WATCH = None


def _call_site() -> str:
    """First stack frame outside this module and the threading module
    (Condition delegates acquire/release through threading.py)."""
    skip = {os.path.abspath(__file__),
            os.path.abspath(threading.__file__)}
    f = sys._getframe(2)
    while f is not None and os.path.abspath(f.f_code.co_filename) in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    parts = f.f_code.co_filename.replace(os.sep, "/").split("/")
    return "/".join(parts[-2:]) + f":{f.f_lineno}"


class _Watch:
    """The process-global order graph + per-thread held stacks."""

    def __init__(self):
        self._mu = threading.Lock()     # guards the graph (plain lock:
        self._after: dict = {}          # instrumenting it would recurse)
        self._sites: dict = {}          # (a, b) -> witness site of the
        self._tls = threading.local()   # b-acquire
        self.edges = 0
        self.checks = 0

    # ---- per-thread stack ----------------------------------------------
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _reachable(self, frm: str, to: str) -> bool:
        seen, work = set(), [frm]
        while work:
            cur = work.pop()
            if cur == to:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(self._after.get(cur, ()))
        return False

    # ---- hooks ----------------------------------------------------------
    def note_acquire(self, name: str) -> None:
        """Called BEFORE blocking on `name`: record the order edges and
        raise on the first cycle — the hang becomes a diagnosis."""
        if getattr(self._tls, "busy", False):
            return          # instrumentation-side lock (metrics): skip
        site = _call_site()
        stack = self._stack()
        self.checks += 1
        inversion = None
        if stack:
            with self._mu:
                for held, _, _ in stack:
                    if held == name or (held, name) in self._sites:
                        continue
                    # an inverse path existing means acquiring now can
                    # deadlock against a thread holding `name`
                    if self._reachable(name, held):
                        inversion = (held, name, site,
                                     self._inverse_witness(name, held))
                        break
                    self._after.setdefault(held, set()).add(name)
                    self._sites[(held, name)] = site
                    self.edges += 1
        if inversion is not None:
            # raise OUTSIDE self._mu: the error's flight-recorder dump
            # may touch instrumented locks (metrics snapshot)
            from superlu_dist_tpu.utils.errors import LockOrderError
            raise LockOrderError(*inversion)
        stack.append((name, site, time.perf_counter()))

    def _inverse_witness(self, frm: str, to: str) -> str:
        """Site of the first edge on a path frm -> ... -> to."""
        direct = self._sites.get((frm, to))
        if direct is not None:
            return direct
        for (a, b), site in self._sites.items():
            if a == frm and self._reachable(b, to):
                return site
        return "<recorded earlier>"

    def note_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, _, t0 = stack.pop(i)
                held_s = time.perf_counter() - t0
                if getattr(self._tls, "busy", False):
                    return      # metrics' own lock: no self-accounting
                self._tls.busy = True
                try:
                    from superlu_dist_tpu.obs.metrics import get_metrics
                    m = get_metrics()
                    if m.enabled:
                        m.observe("slu_lock_hold_seconds", held_s,
                                  lock=name)
                finally:
                    self._tls.busy = False
                return

    def order_graph(self) -> dict:
        with self._mu:
            return {a: sorted(bs) for a, bs in self._after.items()}


class InstrumentedLock:
    """``threading.Lock`` twin feeding the order graph.  Duck-typed to
    the Lock protocol (``Condition`` delegates ``acquire``/``release``
    straight through, so ``make_condition`` wraps one of these)."""

    __slots__ = ("_name", "_inner")

    def __init__(self, name: str):
        self._name = name
        self._inner = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking=True, timeout=-1):
        _WATCH.note_acquire(self._name)
        got = self._inner.acquire(blocking, timeout)
        if not got:
            _WATCH.note_release(self._name)   # never actually held
        return got

    def release(self):
        self._inner.release()
        _WATCH.note_release(self._name)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<InstrumentedLock {self._name!r} {self._inner!r}>"


def make_lock(name: str):
    """A lock participating in the order graph under
    ``SLU_TPU_VERIFY_LOCKS=1``; a PLAIN ``threading.Lock`` otherwise
    (zero wrapper, zero global state — the off path costs nothing)."""
    if not verify_locks_enabled():
        return threading.Lock()
    return InstrumentedLock(name)


def make_condition(name: str, lock=None):
    """A ``threading.Condition``; under verify-lock mode its underlying
    mutex is instrumented (pass the sibling :func:`make_lock` result to
    share ONE identity with it — the ``Condition(self._lock)`` idiom)."""
    if not verify_locks_enabled():
        return threading.Condition(lock)
    if lock is None:
        lock = InstrumentedLock(name)
    return threading.Condition(lock)


def order_graph() -> dict:
    """The current global order graph (empty when verification is off)
    — for tests and postmortems."""
    return _WATCH.order_graph() if _WATCH is not None else {}
