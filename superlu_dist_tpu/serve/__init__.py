from superlu_dist_tpu.serve.server import (   # noqa: F401
    ServerClosedError, SolveServer, SolveTicket)
