"""Multi-process expert driver over block-row distributed input.

Capability analog of pdgssvx with NR_loc input (SRC/pdgssvx.c:505): every
process holds a block of rows of A and of b (`DistributedCSR` — the
NRformat_loc analog), and all of them receive the solution.

TPU-native split: the analysis + factorization are single-address-space
(they run where the accelerator is — rank 0), so the distributed input is
first assembled there, exactly like the reference's
pdCompRow_loc_to_CompCol_global gather before serial preprocessing
(pdgssvx.c:775).  The gather/broadcast ride the shared-memory tree
collectives (parallel/treecomm.py); refinement then runs distributed
(parallel/pgsrfs.py) so the residual work stays with the row owners —
the reference's pdgsrfs/pdgsmv shape.

Payloads larger than the tree domain's max_len stream through in chunks;
integer index arrays travel as f64 (exact below 2^53 — matrix dimensions
and nnz counts are far below).
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.parallel.dist import DistributedCSR
from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.sparse.formats import SparseCSR


def _chunked_reduce(tc: TreeComm, full: np.ndarray, root: int):
    """Sum-reduce a long vector in max_len chunks (every rank calls with
    its zero-padded contribution; disjoint supports => concatenation)."""
    out = np.empty_like(full)
    step = tc.max_len
    for lo in range(0, len(full), step):
        hi = min(lo + step, len(full))
        out[lo:hi] = tc.reduce_sum(full[lo:hi].astype(np.float64),
                                   root=root)[:hi - lo]
    return out


def _chunked_bcast(tc: TreeComm, full: np.ndarray, root: int):
    out = np.empty(len(full))
    step = tc.max_len
    for lo in range(0, len(full), step):
        hi = min(lo + step, len(full))
        out[lo:hi] = tc.bcast(full[lo:hi].astype(np.float64),
                              root=root)[:hi - lo]
    return out


def gather_distributed(tc: TreeComm, a_loc: DistributedCSR,
                       root: int = 0) -> SparseCSR | None:
    """Assemble the global CSR on `root` from every rank's block rows —
    the pdCompRow_loc_to_CompCol_global analog over tree collectives.
    Returns the matrix on root, None elsewhere."""
    n = a_loc.n
    # global nnz offsets: every rank's count, allreduced
    counts = np.zeros(tc.n_ranks)
    counts[tc.rank] = a_loc.nnz_loc
    counts = tc.allreduce_sum(counts, root=root)
    offs = np.zeros(tc.n_ranks + 1, dtype=np.int64)
    offs[1:] = np.cumsum(counts).astype(np.int64)
    total = int(offs[-1])
    lo = int(offs[tc.rank])

    # row counts (for indptr) and flat index/value arrays, disjoint slots
    rowcnt = np.zeros(n)
    rowcnt[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = \
        np.diff(a_loc.indptr)
    rowcnt = _chunked_reduce(tc, rowcnt, root)
    idx = np.zeros(total)
    idx[lo:lo + a_loc.nnz_loc] = a_loc.indices
    idx = _chunked_reduce(tc, idx, root)
    vals = np.zeros(total)
    vals[lo:lo + a_loc.nnz_loc] = a_loc.data
    vals = _chunked_reduce(tc, vals, root)

    if tc.rank != root:
        return None
    indptr = np.zeros(n + 1, dtype=np.int64)
    indptr[1:] = np.cumsum(rowcnt).astype(np.int64)
    # ranks hold contiguous ascending row blocks, so the flat order by
    # rank offset IS row order
    return SparseCSR(n, n, indptr, idx.astype(np.int64), vals)


def pgssvx(tc: TreeComm, options, a_loc: DistributedCSR,
           b_loc: np.ndarray, root: int = 0):
    """Collectively solve A·x = b from block-row distributed input.

    Returns (x_full, info) on every rank.  Single RHS.  The root runs the
    full gssvx pipeline (with its accelerator, if any); refinement is
    distributed across the row owners (pgsrfs).
    """
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    from superlu_dist_tpu.utils.options import IterRefine
    import dataclasses

    n = a_loc.n
    a_root = gather_distributed(tc, a_loc, root=root)
    b_full = np.zeros(n)
    b_full[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = b_loc
    b_full = _chunked_reduce(tc, b_full, root)

    x0 = np.zeros(n)
    info = np.zeros(1)
    solve_fn = None
    if tc.rank == root:
        # refinement happens distributed below — root factors only
        opts0 = dataclasses.replace(options,
                                    iter_refine=IterRefine.NOREFINE)
        x_r, lu, stats, info_r = gssvx(opts0, a_root, b_full)
        info[0] = float(info_r)
        if info_r == 0:
            x0 = np.asarray(x_r, dtype=np.float64)
            solve_fn = lu.solve_factored
    info = tc.bcast(info, root=root)
    if int(info[0]) != 0:
        return None, int(info[0])
    x0 = _chunked_bcast(tc, x0, root)
    if options.iter_refine == IterRefine.NOREFINE:
        return x0, 0
    x = pgsrfs(tc, a_loc, b_loc, x0, solve_fn, root=root)
    return x, 0
