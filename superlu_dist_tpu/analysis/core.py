"""slulint engine: finding model, rule base class, suppressions, driver.

A project-native static-analysis layer (the correctness-tooling
discipline production solver stacks grow — ShyLU's node-level test/check
harnesses are the PAPERS.md precedent): generic linters cannot know that
every rank must reach the same TreeComm collective sequence, that hot
kernels must stay trace-pure, or that nnz/offset accumulators must
survive the int32/int64 index-width selection (the reference's ``int_t``
discipline, superlu_defs.h:80-93).  The rules in rules_*.py encode those
invariants as lexical AST checks.

Design points:

* Rules are :class:`ast.NodeVisitor`-style walkers producing
  :class:`Finding` records (rule id, file:line:col, message, fix hint).
* ``# slulint: disable=SLU101`` on a flagged line suppresses it;
  ``# slulint: disable-file=SLU104`` anywhere in the first 20 lines
  suppresses a rule for a whole file.  Suppressions are meant to carry a
  justification in the same comment.
* A committed JSON baseline (baseline.py) grandfathers known findings so
  the CI gate (scripts/run_slulint.sh) only fails on NEW ones.
* Everything is lexical — no imports of the analyzed code, no type
  inference.  False-negative-leaning by design: a quiet rule that only
  fires on the known-deadly shapes earns trust; a noisy one gets
  disabled.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

#: bumped whenever the rule set / engine semantics change — part of the
#: result-cache key (analysis/cache.py), so a stale cache can never
#: serve findings computed by an older rule set
ANALYSIS_VERSION = "6"


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def render(self) -> str:
        out = f"{self.location()}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class Rule:
    """Base class for slulint rules.

    Subclasses set ``rule_id``/``title``/``hint`` and implement
    ``check(tree, source, path, project=None) -> list[Finding]``.
    ``project`` is the package-wide call graph + dataflow summaries
    (analysis.callgraph.Project) when the driver built one — rules use
    it for interprocedural reasoning and must degrade to their lexical
    behavior when it is None.  ``package_dirs`` restricts a rule to
    subpackages *within* the superlu_dist_tpu tree (hot-path rules like
    trace-purity only make sense there); files outside the package —
    scripts, test fixtures — are always in scope.
    """

    rule_id: str = "SLU1xx"
    title: str = ""
    hint: str = ""
    package_dirs: tuple | None = None

    def applies(self, path: str) -> bool:
        parts = _norm_parts(path)
        if self.package_dirs is None or "superlu_dist_tpu" not in parts:
            return True
        return any(d in parts for d in self.package_dirs)

    def check(self, tree: ast.AST, source: str, path: str,
              project=None) -> list:
        raise NotImplementedError

    def finding(self, path: str, node: ast.AST, message: str,
                hint: str | None = None) -> Finding:
        return Finding(self.rule_id, path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0) + 1, message,
                       self.hint if hint is None else hint)


def _norm_parts(path: str) -> tuple:
    return tuple(os.path.normpath(path).split(os.sep))


# --- shared AST helpers -----------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """'np.add.at' for Attribute/Name chains, '' for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_env_read(node: ast.AST):
    """Match os.environ.get('K') / os.environ['K'] / os.getenv('K') /
    os.environ.setdefault('K', ...) / 'K' in os.environ.  Returns
    (key-or-None, anchor-node) or None.  Writes are not reads (exporting
    to subprocesses is legitimate); non-literal keys return key=None.
    """
    def lit(args):
        if args and isinstance(args[0], ast.Constant) \
                and isinstance(args[0].value, str):
            return args[0].value
        return None

    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn.endswith("os.getenv") or fn == "getenv":
            return lit(node.args), node
        if fn.endswith("environ.get") or fn.endswith("environ.setdefault"):
            return lit(node.args), node
    if isinstance(node, ast.Subscript):
        base = dotted_name(node.value)
        if base.endswith("environ") and isinstance(node.ctx, ast.Load):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return sl.value, node
            return None, node
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)):
        if dotted_name(node.comparators[0]).endswith("environ"):
            left = node.left
            if isinstance(left, ast.Constant) and isinstance(left.value, str):
                return left.value, node
            return None, node
    return None


# --- suppressions -----------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*slulint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*slulint:\s*disable-file=([A-Za-z0-9_,\s]+)")
_FILE_SUPPRESS_SCAN_LINES = 20


def _parse_ids(blob: str) -> set:
    return {p.strip() for p in blob.split(",") if p.strip()}


def suppressions(source: str):
    """(line -> suppressed rule ids, file-wide suppressed rule ids)."""
    per_line: dict = {}
    file_wide: set = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m and i <= _FILE_SUPPRESS_SCAN_LINES:
            file_wide |= _parse_ids(m.group(1))
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            per_line.setdefault(i, set()).update(_parse_ids(m.group(1)))
    return per_line, file_wide


# --- driver -----------------------------------------------------------------

PARSE_ERROR_RULE = "SLU100"


def default_rules() -> list:
    from superlu_dist_tpu.analysis.rules_collective import CollectiveRule
    from superlu_dist_tpu.analysis.rules_trace import (
        JitCacheKeyRule, JitKeyShapeDiversityRule, TracePurityRule)
    from superlu_dist_tpu.analysis.rules_index import IndexWidthRule
    from superlu_dist_tpu.analysis.rules_env import EnvKnobRule
    from superlu_dist_tpu.analysis.rules_shared import SharedMutableRule
    from superlu_dist_tpu.analysis.rules_lockorder import LockOrderRule
    from superlu_dist_tpu.analysis.rules_lifecycle import \
        ThreadLifecycleRule
    from superlu_dist_tpu.analysis.rules_program import HostRoundTripRule
    from superlu_dist_tpu.analysis.rules_precision import (
        AccumulationDtypeRule, EFTPurityRule, ImplicitDowncastRule,
        ToleranceLiteralRule)
    from superlu_dist_tpu.analysis.rules_sharding import (
        CrossMeshTransferRule, ImplicitReshardRule, MeshSpecHygieneRule,
        PeakMemoryRule)
    return [CollectiveRule(), TracePurityRule(), IndexWidthRule(),
            EnvKnobRule(), JitCacheKeyRule(), JitKeyShapeDiversityRule(),
            SharedMutableRule(), LockOrderRule(), ThreadLifecycleRule(),
            HostRoundTripRule(), ImplicitDowncastRule(),
            AccumulationDtypeRule(), EFTPurityRule(),
            ToleranceLiteralRule(), ImplicitReshardRule(),
            MeshSpecHygieneRule(), PeakMemoryRule(),
            CrossMeshTransferRule()]


def analyze_source(source: str, path: str, rules, project=None) -> list:
    """Run `rules` over one file.  With ``project=None`` a single-file
    project (call graph + dataflow summaries of just this source) is
    built, so intra-module interprocedural reasoning works even for
    isolated fixtures; the driver passes the package-wide project when
    scanning a tree."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(PARSE_ERROR_RULE, path, exc.lineno or 0, 1,
                        f"file does not parse: {exc.msg}",
                        "slulint gates on parseability so every rule "
                        "actually ran")]
    if project is None:
        from superlu_dist_tpu.analysis.callgraph import build_project
        project = build_project({path: (source, tree)})
    per_line, file_wide = suppressions(source)
    out = []
    for rule in rules:
        if not rule.applies(path):
            continue
        for f in rule.check(tree, source, path, project):
            if f.rule in file_wide or f.rule in per_line.get(f.line, ()):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


_SKIP_DIRS = {".git", "__pycache__", ".cache", ".venv", "node_modules",
              "build", "dist"}


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS
                             and not d.endswith(".egg-info"))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def read_sources(paths) -> dict:
    sources = {}
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as fh:
            sources[path] = fh.read()
    return sources


def analyze_sources(sources: dict, rules=None) -> list:
    """Whole-tree scan: ONE project (call graph + summaries) spanning
    every file, so cross-module indirection resolves."""
    from superlu_dist_tpu.analysis.callgraph import build_project
    rules = default_rules() if rules is None else rules
    project = build_project(sources)
    out = []
    for path, source in sources.items():
        out.extend(analyze_source(source, path, rules, project))
    return out


def analyze_paths(paths, rules=None) -> list:
    return analyze_sources(read_sources(paths), rules)
