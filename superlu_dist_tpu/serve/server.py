"""Micro-batching solve server — the serving tier over a factored handle.

``SolveServer`` owns one factored :class:`LUFactorization` (taken live
from a ``gssvx`` result, or loaded zero-refactor from a ``persist/``
bundle via :meth:`SolveServer.from_bundle` — FACT time stays 0.0) and
turns "one matrix, one solve" into a request/response loop:

* callers :meth:`submit` right-hand-side columns (original labeling,
  ``A·x = b``) and get a :class:`SolveTicket` back immediately;
* a dispatcher thread coalesces pending columns into micro-batches
  **keyed to the device solver's compiled nrhs buckets** (solve/plan.py)
  — the oldest pending request is held open for at most
  ``SLU_TPU_SERVE_MAX_WAIT_MS`` so concurrent traffic lands in one
  device dispatch instead of many, and a batch dispatches early the
  moment it can fill ``SLU_TPU_SERVE_MAX_BATCH`` columns (default: the
  nrhs bucket cap);
* each batch is ONE solve through the handle (device sweeps on an
  accelerator backend, the host supernodal solve otherwise — the same
  auto/fallback discipline as the driver), whose results are scattered
  back to the submitting tickets.

Requests wider than the batch cap are column-split across consecutive
batches transparently — a ticket completes when all its columns have.

Observability: every batch runs under a ``serve-batch`` dispatch span
(the device solve's own ``device-solve`` kernel span and ``solve-d2h``
comm span nest inside it), and the metrics registry (obs/metrics.py,
``SLU_TPU_METRICS``) accumulates the serving-grade series —
``slu_serve_requests_total`` / ``_columns_total`` / ``_batches_total``
/ ``_errors_total`` counters, the ``slu_serve_queue_depth`` gauge, and
``slu_serve_request_seconds`` / ``slu_serve_batch_fill`` histograms
(per-request latency, batch occupancy).  docs/SERVING.md walks the
whole tier.
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.solve.plan import bucket_nrhs
from superlu_dist_tpu.utils.errors import SuperLUError


class ServerClosedError(SuperLUError):
    """submit() after close() — the request was never enqueued."""


class _Request:
    """One submitted right-hand side, possibly column-split over several
    micro-batches; completes when every column has been solved."""

    __slots__ = ("b", "k", "squeeze", "remaining", "parts", "error",
                 "t_submit", "event")

    def __init__(self, b: np.ndarray, squeeze: bool):
        self.b = b
        self.k = b.shape[1]
        self.squeeze = squeeze
        self.remaining = self.k
        self.parts = []          # [(col offset, solved columns array)]
        self.error = None
        self.t_submit = time.perf_counter()
        self.event = threading.Event()


class SolveTicket:
    """Handle for one submitted request (future-style)."""

    def __init__(self, req: _Request):
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Block until the request's solve completes and return x with
        the submitted shape ((n,) stays (n,)).  Raises the batch's error
        if its dispatch failed, TimeoutError on expiry."""
        if not self._req.event.wait(timeout):
            raise TimeoutError(
                f"solve request ({self._req.k} columns) not served "
                f"within {timeout}s")
        req = self._req
        if req.error is not None:
            raise req.error
        parts = sorted(req.parts, key=lambda p: p[0])
        x = (parts[0][1] if len(parts) == 1
             else np.concatenate([p[1] for p in parts], axis=1))
        return x[:, 0] if req.squeeze else x


class SolveServer:
    """Micro-batching solve service over one factored handle.

    Parameters
    ----------
    lu : LUFactorization
        A FACTORED handle (``lu.numeric`` present) — from a live
        ``gssvx`` call or ``persist.load_lu``.
    max_batch : int
        Micro-batch column cap; 0/None reads ``SLU_TPU_SERVE_MAX_BATCH``
        (whose 0 default means: the device solve's nrhs bucket cap).
    max_wait_s : float
        Coalescing window; None reads ``SLU_TPU_SERVE_MAX_WAIT_MS``.
    trans / conj :
        Serve ``AᵀX = B`` (``AᴴX = B``) through the same factors.
    start : bool
        Spawn the dispatcher immediately; ``start=False`` lets tests
        enqueue a deterministic backlog first, then :meth:`start`.
    """

    def __init__(self, lu, max_batch: int | None = None,
                 max_wait_s: float | None = None, trans: bool = False,
                 conj: bool = False, start: bool = True):
        from superlu_dist_tpu.utils.options import env_float, env_int
        if lu is None or lu.numeric is None:
            raise SuperLUError(
                "SolveServer requires a FACTORED handle (lu.numeric is "
                "None — factor first, or load a persisted bundle via "
                "SolveServer.from_bundle)")
        self.lu = lu
        self.n = int(lu.n)
        self.trans = bool(trans)
        self.conj = bool(conj)
        self._solve = (
            (lambda b: lu.solve_factored_trans(b, conj=self.conj))
            if self.trans else lu.solve_factored)
        from superlu_dist_tpu.solve.plan import nrhs_buckets
        buckets = nrhs_buckets(env_int("SLU_TPU_SOLVE_NRHS_MAX"),
                               env_float("SLU_TPU_SOLVE_NRHS_GROWTH"))
        if not max_batch:
            max_batch = env_int("SLU_TPU_SERVE_MAX_BATCH")
        if not max_batch:
            max_batch = buckets[-1]     # the nrhs bucket cap
        self.max_batch = int(max_batch)
        # the batch sizes this server targets: the compiled nrhs buckets
        # up to (and always including) its own cap
        self._bucket_set = tuple(
            b for b in buckets if b < self.max_batch) + (self.max_batch,)
        if max_wait_s is None:
            max_wait_s = env_float("SLU_TPU_SERVE_MAX_WAIT_MS") / 1000.0
        self.max_wait_s = float(max_wait_s)
        self.source = "live"
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # queue of [request, columns-already-taken] — a wide request
        # drains across batches without blocking narrower traffic
        self._queue: collections.deque = collections.deque()
        self._pending_cols = 0
        self._closed = False
        self._flush = False
        self._thread = None
        # totals (under _lock); the metrics registry mirrors them when on
        self._requests = 0
        self._columns = 0
        self._batches = 0
        self._batch_cols = 0
        self._errors = 0
        self._metrics = m = get_metrics()
        self._metrics = m if m.enabled else None
        if start:
            self.start()

    # ------------------------------------------------------------------
    @classmethod
    def from_bundle(cls, dirpath: str, **kw) -> "SolveServer":
        """Serve from a persisted LU bundle (persist/serial.save_lu):
        the handle loads digest-verified and solves with ZERO
        refactorization — the warm-start path a serving fleet restarts
        through (FACT time stays 0.0; docs/RELIABILITY.md)."""
        from superlu_dist_tpu.persist.serial import load_lu
        srv = cls(load_lu(dirpath), **kw)
        srv.source = str(dirpath)
        return srv

    # ------------------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="slu-serve-dispatch",
                daemon=True)
            self._thread.start()
        return self

    def submit(self, b: np.ndarray) -> SolveTicket:
        """Enqueue one right-hand side — (n,) or (n, k), original
        labeling — and return its ticket immediately."""
        b = np.asarray(b)
        squeeze = b.ndim == 1
        b2 = b[:, None] if squeeze else b
        if b2.ndim != 2 or b2.shape[0] != self.n or b2.shape[1] == 0:
            raise SuperLUError(
                f"rhs shape {b.shape} does not fit an n={self.n} serve "
                "handle (need (n,) or (n, k>0))")
        req = _Request(b2, squeeze)
        with self._cond:
            if self._closed:
                raise ServerClosedError("SolveServer is closed")
            self._queue.append([req, 0])
            self._pending_cols += req.k
            self._requests += 1
            self._columns += req.k
            depth = self._pending_cols
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.inc("slu_serve_requests_total", 1.0)
            self._metrics.inc("slu_serve_columns_total", float(req.k))
            self._metrics.set("slu_serve_queue_depth", float(depth))
        return SolveTicket(req)

    def solve(self, b: np.ndarray,
              timeout: float | None = None) -> np.ndarray:
        """submit() + result(): the one-call convenience path."""
        return self.submit(b).result(timeout)

    def flush(self):
        """Dispatch whatever is pending without waiting out the
        coalescing window."""
        with self._cond:
            self._flush = True
            self._cond.notify_all()

    def close(self, timeout: float | None = None):
        """Stop accepting work, drain the queue, join the dispatcher."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Serving counters so far (process-local; the metrics registry
        carries the scrapeable twin)."""
        with self._lock:
            batches = self._batches
            return {
                "requests": self._requests,
                "columns": self._columns,
                "batches": batches,
                "errors": self._errors,
                "queue_depth": self._pending_cols,
                "mean_batch_columns": (round(self._batch_cols / batches, 2)
                                       if batches else 0.0),
                "max_batch": self.max_batch,
                "max_wait_s": self.max_wait_s,
                "source": self.source,
                "closed": self._closed,
            }

    # ------------------------------------------------------------------
    def _take_batch(self):
        """Under the lock: carve up to max_batch columns off the queue
        head.  Returns [(request, req_lo, req_hi), ...] (empty on
        shutdown with a drained queue)."""
        segs = []
        total = 0
        while self._queue and total < self.max_batch:
            entry = self._queue[0]
            req, off = entry
            take = min(req.k - off, self.max_batch - total)
            segs.append((req, off, off + take))
            total += take
            if off + take == req.k:
                self._queue.popleft()
            else:
                entry[1] = off + take
        self._pending_cols -= total
        return segs

    def _dispatch_loop(self):
        tracer = get_tracer()
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._flush = False
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                # coalescing: hold the oldest request open for the
                # batching window unless the batch can already fill (or
                # a flush/close asked for immediacy)
                deadline = time.perf_counter() + self.max_wait_s
                while (self._pending_cols < self.max_batch
                       and not self._closed and not self._flush):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                self._flush = False
                segs = self._take_batch()
                depth = self._pending_cols
            if not segs:
                continue
            self._dispatch(segs, depth, tracer)

    def _dispatch(self, segs, depth, tracer):
        cols = sum(hi - lo for _, lo, hi in segs)
        kb = bucket_nrhs(min(cols, self.max_batch), self._bucket_set)
        t0 = time.perf_counter()
        try:
            if len(segs) == 1:
                req, lo, hi = segs[0]
                mat = req.b[:, lo:hi]
            else:
                dtype = np.result_type(*(s[0].b.dtype for s in segs))
                mat = np.empty((self.n, cols), dtype=dtype)
                c = 0
                for req, lo, hi in segs:
                    mat[:, c:c + hi - lo] = req.b[:, lo:hi]
                    c += hi - lo
            with tracer.span("serve-batch", cat="dispatch", columns=cols,
                             bucket=kb, requests=len(segs),
                             queue_depth=depth, trans=self.trans):
                x = np.asarray(self._solve(mat))
            err = None
        except Exception as e:          # noqa: BLE001 — the error belongs
            x, err = None, e            # to the tickets, not the loop
        now = time.perf_counter()
        done_lat = []
        with self._lock:
            self._batches += 1
            self._batch_cols += cols
            if err is not None:
                self._errors += 1
        c = 0
        for req, lo, hi in segs:
            if err is not None:
                req.error = err
                req.event.set()
            else:
                req.parts.append((lo, x[:, c:c + hi - lo]))
                req.remaining -= hi - lo
                if req.remaining == 0:
                    done_lat.append(now - req.t_submit)
                    req.event.set()
            c += hi - lo
        m = self._metrics
        if m is not None:
            m.inc("slu_serve_batches_total", 1.0)
            m.set("slu_serve_queue_depth", float(depth))
            m.observe("slu_serve_batch_fill", cols / max(kb, 1))
            m.set("slu_serve_batch_seconds", now - t0)
            if err is not None:
                m.inc("slu_serve_errors_total", 1.0,
                      error=type(err).__name__)
            for lat in done_lat:
                m.observe("slu_serve_request_seconds", lat)
