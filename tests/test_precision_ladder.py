"""Throughput-ladder tests: mixed-precision Schur GEMMs with BERR-gated
escalation (ops/dense.gemm_precision, drivers/gssvx gemm-precision rung)
and the Pallas fused gather/scatter kernels (numeric/pallas_kernels.py).

The contract under test (docs/PERFORMANCE.md, throughput ladder):

* every GEMM tier DELIVERS componentwise BERR at or below the gate —
  reduced tiers may escalate (the rung is recorded), but a failing X is
  never returned as converged;
* the executors stay bitwise-identical to each other WITHIN a tier, and
  the Pallas extend-add/assembly path is bitwise-identical to the
  ``.at[]`` lowering (so every older equivalence gate carries over);
* a checkpoint frontier computed at one tier refuses to resume under
  another tier's arithmetic.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import (
    hilbert, poisson2d, rank_deficient_arrowhead)
from superlu_dist_tpu.numeric.factor import (
    extend_add_set, numeric_factorize)
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.ops.dense import (
    GEMM_PREC_LADDER, gemm, gemm_precision, next_gemm_precision)
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.utils.options import KNOB_REGISTRY, Options

pytestmark = pytest.mark.precision


def _analyzed(a, **plan_kw):
    sym = symmetrize_pattern(a)
    co = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, co)
    plan = build_plan(sf, **plan_kw)
    return plan, sym.data[sf.value_perm], a.norm_max()


def _host_fronts(num):
    return [(np.asarray(lp), np.asarray(up)) for lp, up in num.fronts]


# ---------------------------------------------------------------------------
# tier resolution and the helper semantics
# ---------------------------------------------------------------------------

def test_tier_resolution_and_env(monkeypatch):
    monkeypatch.delenv("SLU_TPU_GEMM_PREC", raising=False)
    monkeypatch.delenv("SLU_TPU_PRECISION", raising=False)
    assert gemm_precision() == "default"          # the fast-path default
    assert gemm_precision("bf16") == "bf16"       # explicit wins
    monkeypatch.setenv("SLU_TPU_GEMM_PREC", "f32")
    assert gemm_precision() == "f32"
    # legacy knob interop: an explicitly-set SLU_TPU_PRECISION keeps
    # meaning what it always meant when the new knob is unset
    monkeypatch.delenv("SLU_TPU_GEMM_PREC")
    monkeypatch.setenv("SLU_TPU_PRECISION", "high")
    assert gemm_precision() == "f32"
    monkeypatch.setenv("SLU_TPU_PRECISION", "highest")
    assert gemm_precision() == "highest"
    monkeypatch.setenv("SLU_TPU_GEMM_PREC", "bogus")
    with pytest.raises(ValueError):
        gemm_precision()


def test_ladder_order_and_cpu_noop_steps():
    assert GEMM_PREC_LADDER == ("bf16", "default", "f32", "highest")
    # CPU executes every lax.Precision identically: the only escalation
    # step that changes arithmetic is crossing the bf16 input cast
    assert next_gemm_precision("bf16", backend="cpu") == "default"
    assert next_gemm_precision("default", backend="cpu") is None
    assert next_gemm_precision("highest", backend="cpu") is None
    # accelerators walk every rung
    assert next_gemm_precision("bf16", backend="tpu") == "default"
    assert next_gemm_precision("default", backend="tpu") == "f32"
    assert next_gemm_precision("f32", backend="tpu") == "highest"
    assert next_gemm_precision("highest", backend="tpu") is None


def test_gemm_helper_semantics():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((16, 8)), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal((8, 12)), dtype=jnp.float32)
    exact = np.asarray(a) @ np.asarray(b)
    # non-bf16 tiers on CPU are full f32 math (bitwise-identical to one
    # another — CPU ignores lax.Precision) and dtype-preserving
    ref = None
    for tier in ("default", "f32", "highest"):
        out = gemm(a, b, tier)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), exact, rtol=1e-4)
        if ref is None:
            ref = np.asarray(out)
        else:
            assert (np.asarray(out) == ref).all()
    # bf16 tier truncates inputs but accumulates at f32 and returns f32
    out = gemm(a, b, "bf16")
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), exact, rtol=2e-2,
                               atol=2e-2)
    assert float(np.max(np.abs(np.asarray(out) - exact))) > 0.0
    # complex operands have no bf16 carrier: degrade to default, exact
    ac = a.astype(jnp.complex64)
    bc = b.astype(jnp.complex64)
    outc = gemm(ac, bc, "bf16")
    assert outc.dtype == jnp.complex64
    np.testing.assert_allclose(np.asarray(outc).real, exact, rtol=1e-4,
                               atol=1e-5)


def test_new_knobs_registry_routed():
    """SLU104 satellite: the ladder knobs are registry-declared, so the
    slulint env rule covers their reads (the tree scans clean)."""
    for name in ("SLU_TPU_GEMM_PREC", "SLU_TPU_PALLAS",
                 "SLU_TPU_PEAK_GFLOPS"):
        assert name in KNOB_REGISTRY, name


# ---------------------------------------------------------------------------
# delivered accuracy: BERR <= gate at every tier, escalation recorded
# ---------------------------------------------------------------------------

GALLERY = (
    ("poisson", lambda: poisson2d(12)),
    ("hilbert", lambda: hilbert(8)),
    ("arrowhead", lambda: rank_deficient_arrowhead(n=60, delta=1e-6,
                                                   seed=0)),
)


@pytest.mark.parametrize("tier", ["bf16", "f32", "highest"])
@pytest.mark.parametrize("name,make", GALLERY, ids=[g[0] for g in GALLERY])
def test_delivered_berr_every_tier(name, make, tier):
    """Gallery × tier: whatever the tier gambles, the DELIVERED berr
    meets the gate (escalation allowed and recorded — never a failing X
    reported converged)."""
    a = make()
    xt = np.random.default_rng(1).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(Options(gemm_prec=tier,
                                       factor_dtype="float32"), a, b)
    assert info == 0
    rep = stats.solve_report
    assert np.all(np.isfinite(x))
    assert rep.converged and rep.berr is not None
    assert rep.berr <= rep.target, rep.summary()
    # the report names the tier the ANSWER rests on (post-escalation)
    assert rep.gemm_precision in GEMM_PREC_LADDER


def test_escalation_rung_fires_on_hilbert_bf16():
    """hilbert(8) at the bf16 tier misses the f64-class gate on the raw
    factors: the gemm-precision rung must fire, be recorded, and the
    ladder must still deliver a converged answer."""
    a = hilbert(8)
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = gssvx(Options(gemm_prec="bf16",
                                       factor_dtype="float32"), a, b)
    assert info == 0
    rep = stats.solve_report
    names = [r.name for r in rep.rungs]
    assert "gemm-precision" in names, rep.summary()
    assert rep.converged and rep.berr <= rep.target, rep.summary()
    # the adopted handle is the escalated one, and the report reflects
    # what the answer actually rests on (tier and/or dtype moved up)
    assert (rep.gemm_precision != "bf16"
            or rep.factor_dtype != "float32"), rep.summary()


def test_norefine_still_gated_on_reduced_tier():
    """Opting out of IR is not opting out of the BERR gate: NOREFINE at
    a reduced tier still probes componentwise berr and escalates on a
    miss (check_precision_safety.py gate, phase A twin)."""
    from superlu_dist_tpu.utils.options import IterRefine
    a = hilbert(8)
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = gssvx(
        Options(gemm_prec="bf16", factor_dtype="float32",
                iter_refine=IterRefine.NOREFINE), a, b)
    assert info == 0
    rep = stats.solve_report
    assert rep.berr is not None and rep.target is not None
    assert rep.converged and rep.berr <= rep.target, rep.summary()
    assert rep.rungs, "reduced-tier NOREFINE miss must escalate"


def test_well_conditioned_fast_tier_no_rungs():
    """The fast path on a well-conditioned system converges with ZERO
    ladder actions — the gamble costs nothing when it pays off."""
    a = poisson2d(12)
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = gssvx(Options(gemm_prec="bf16"), a, b)
    assert info == 0
    rep = stats.solve_report
    assert rep.converged and rep.rungs == []
    assert rep.gemm_precision == "bf16"


# ---------------------------------------------------------------------------
# executor equivalence per tier + Pallas bitwise contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tier", ["bf16", "highest"])
def test_bitwise_mega_stream_fused_per_tier(tier):
    a = poisson2d(14)
    plan, vals, anorm = _analyzed(a, closed=True)
    outs = {}
    for ex in ("fused", "stream", "mega"):
        num = numeric_factorize(plan, vals, anorm, dtype="float32",
                                executor=ex, gemm_prec=tier)
        assert num.gemm_prec == tier
        outs[ex] = _host_fronts(num)
    for ex in ("stream", "mega"):
        for (bl, bu), (ol, ou) in zip(outs["fused"], outs[ex]):
            assert (bl == ol).all() and (bu == ou).all(), \
                f"{ex} != fused at tier {tier}"


def test_tiers_actually_differ_bf16():
    """bf16 vs highest factors of the same plan must NOT be bitwise
    equal — otherwise the ladder is a no-op and the 3x is fiction."""
    a = poisson2d(14)
    plan, vals, anorm = _analyzed(a)
    hi = _host_fronts(numeric_factorize(plan, vals, anorm,
                                        dtype="float32",
                                        executor="fused",
                                        gemm_prec="highest"))
    lo = _host_fronts(numeric_factorize(plan, vals, anorm,
                                        dtype="float32",
                                        executor="fused",
                                        gemm_prec="bf16"))
    assert any((h[0] != l[0]).any() or (h[1] != l[1]).any()
               for h, l in zip(hi, lo))


def test_pallas_extend_add_unit_bitwise():
    """Unit contract: the Pallas extend-add equals the .at[] lowering
    BITWISE, padded sentinels (OOB pool offset, OOB slot, rel == m)
    included."""
    from superlu_dist_tpu.numeric.pallas_kernels import (
        extend_add_set_pallas)
    rng = np.random.default_rng(3)
    m, ub, batch, pool_len = 12, 5, 3, 200
    pool = jnp.asarray(rng.standard_normal(pool_len), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((batch, m * m)),
                    dtype=jnp.float32)
    child_off = jnp.asarray([0, 25, 50, pool_len])   # last = padding
    child_slot = jnp.asarray([1, 0, 1, batch])
    rel = np.full((4, ub), m, dtype=np.int64)
    for c in range(3):
        rel[c, :4] = rng.choice(m, size=4, replace=False)
    rel = jnp.asarray(rel)
    ref = extend_add_set(f, pool, m, ub, child_off, child_slot, rel)
    out = extend_add_set_pallas(f, pool, m, ub, child_off, child_slot,
                                rel, mode="interpret")
    assert (np.asarray(ref) == np.asarray(out)).all()


def test_pallas_assembly_unit_bitwise():
    from superlu_dist_tpu.numeric.pallas_kernels import (
        assemble_avals_pallas)
    rng = np.random.default_rng(4)
    batch, m, n_avals, la = 4, 9, 50, 37
    avals = jnp.asarray(rng.standard_normal(n_avals), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((batch, m * m)),
                    dtype=jnp.float32)
    pairs = rng.choice(batch * m * m, size=30, replace=False)
    a_slot = np.concatenate([pairs // (m * m), np.full(la - 30, batch)])
    a_flat = np.concatenate([pairs % (m * m),
                             np.zeros(la - 30, dtype=np.int64)])
    a_src = np.concatenate([rng.integers(0, n_avals, 30),
                            np.full(la - 30, n_avals)])
    a_slot, a_flat, a_src = map(jnp.asarray, (a_slot, a_flat, a_src))
    vals = avals.at[a_src].get(mode="fill", fill_value=0)
    ref = f.at[(a_slot, a_flat)].add(vals, mode="drop")
    out = assemble_avals_pallas(f, avals, a_slot, a_flat, a_src,
                                mode="interpret")
    assert (np.asarray(ref) == np.asarray(out)).all()


@pytest.mark.parametrize("executor", ["fused", "stream", "mega"])
def test_pallas_end_to_end_bitwise(executor, monkeypatch):
    """The real factor path under SLU_TPU_PALLAS=interpret is bitwise
    vs the .at[] lowering, per executor (assembly + extend-add both
    exercised)."""
    a = poisson2d(14)
    plan, vals, anorm = _analyzed(a, closed=True)
    monkeypatch.delenv("SLU_TPU_PALLAS", raising=False)
    base = _host_fronts(numeric_factorize(plan, vals, anorm,
                                          dtype="float32",
                                          executor=executor))
    monkeypatch.setenv("SLU_TPU_PALLAS", "interpret")
    pal = _host_fronts(numeric_factorize(plan, vals, anorm,
                                         dtype="float32",
                                         executor=executor))
    for (bl, bu), (pl_, pu) in zip(base, pal):
        assert (bl == pl_).all() and (bu == pu).all()


def test_pallas_mode_resolution(monkeypatch):
    from superlu_dist_tpu.numeric.pallas_kernels import pallas_mode
    monkeypatch.delenv("SLU_TPU_PALLAS", raising=False)
    assert pallas_mode() == "off"        # auto on a CPU backend
    monkeypatch.setenv("SLU_TPU_PALLAS", "0")
    assert pallas_mode() == "off"
    monkeypatch.setenv("SLU_TPU_PALLAS", "interpret")
    assert pallas_mode() == "interpret"
    monkeypatch.setenv("SLU_TPU_PALLAS", "1")
    assert pallas_mode() == "interpret"  # forced-on degrades off-TPU
    monkeypatch.setenv("SLU_TPU_PALLAS", "nope")
    with pytest.raises(ValueError):
        pallas_mode()


# ---------------------------------------------------------------------------
# checkpoint identity + peak table
# ---------------------------------------------------------------------------

def test_checkpoint_refuses_cross_tier_resume(tmp_path):
    from superlu_dist_tpu.persist.checkpoint import (
        FactorCheckpointer, load_checkpoint)
    from superlu_dist_tpu.utils.errors import CheckpointMismatchError
    a = poisson2d(8)
    plan, vals, anorm = _analyzed(a)
    thresh = np.float32(1e-8)
    ck = FactorCheckpointer(str(tmp_path), plan, vals.astype(np.float32),
                            thresh, "float32", gemm_prec="bf16")
    ck.flush(0, [], np.zeros(plan.pool_size, np.float32), 0,
             reason="test")
    ck.complete(cleanup=False)
    st = load_checkpoint(str(tmp_path), plan=plan,
                         pattern_values=vals.astype(np.float32),
                         thresh=thresh, dtype="float32",
                         gemm_prec="bf16")
    assert st.k == 0
    with pytest.raises(CheckpointMismatchError):
        load_checkpoint(str(tmp_path), plan=plan,
                        pattern_values=vals.astype(np.float32),
                        thresh=thresh, dtype="float32",
                        gemm_prec="highest")


def test_peak_detection_and_mfu(monkeypatch):
    from superlu_dist_tpu.utils.peaks import (
        detect_peak_gflops, mfu_pct, table_peak_gflops)
    monkeypatch.setenv("SLU_TPU_PEAK_GFLOPS", "1000")
    peak, src = detect_peak_gflops("default")
    assert peak == 1000.0 and src == "env"
    pct, p, s = mfu_pct(10.0, "default")
    assert pct == 1.0
    monkeypatch.delenv("SLU_TPU_PEAK_GFLOPS")
    # CPU backend: measured calibration, never the TPU constant
    peak, src = detect_peak_gflops("default")
    assert peak > 0 and src.startswith("measured:")
    pct, _, _ = mfu_pct(peak / 100.0, "default")
    assert pct > 0.0         # never rounds a real rate down to 0.0
    # jax-free table accessor: tier pass-counts divide the bf16 peak
    assert table_peak_gflops("TPU v5e", "bf16") == 197_000.0
    assert table_peak_gflops("TPU v5e", "highest") == pytest.approx(
        197_000.0 / 6)
    assert table_peak_gflops("A100", "bf16") is None


def test_bench_history_key_is_precision_tagged():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench_history", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "bench_history.py"))
    bh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bh)
    base = {"metric": "m", "backend": "cpu", "granularity": "fused",
            "schedule": "dataflow", "blocking": [1, 2]}
    k_hi = bh.row_key({**base, "gemm_precision": "highest"})
    k_lo = bh.row_key({**base, "gemm_precision": "bf16"})
    assert k_hi != k_lo      # no cross-precision baselines
