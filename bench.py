#!/usr/bin/env python
"""Benchmark: sparse LU numeric-factorization GFLOPS, TPU vs host CPU.

The metric mirrors the reference's headline number — factor Mflops printed
by PStatPrint (SRC/util.c:513-518) — on the BASELINE.md config-4 matrix
class (7-pt 3D Poisson).  The numeric factorization runs entirely on the
device via the streamed executor (numeric/stream.py).

vs_baseline is the wall-clock factorization speedup over serial SuperLU
with host CPU BLAS (scipy.sparse.linalg.splu — the same code family as the
reference) factoring the identical matrix on this machine (north-star
target: >= 4x CPU-BLAS factorization, BASELINE.json).  The reference's
distributed pdgstrf on one node is the same computation plus MPI overhead,
so serial SuperLU is the stronger (fairer) baseline.  Note the dtype
asymmetry is part of the design under measure: the TPU path factors in f32
and recovers f64 accuracy via iterative refinement (GESP + IR, SURVEY.md
§7 hard-part 1); the residual printed is AFTER refinement and must be at
reference accuracy.

Prints ONE JSON line:
  {"metric": ..., "value": GFLOPS, "unit": "GFLOP/s", "vs_baseline": ...}

Env knobs: BENCH_NX (grid edge, default 48 -> n=110592), BENCH_REPS,
BENCH_PEAK_F32_TFLOPS (MFU denominator).
"""

import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".cache", "jax"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

from superlu_dist_tpu.models.gallery import poisson3d
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.stream import StreamExecutor
from superlu_dist_tpu.numeric.factor import NumericFactorization
from superlu_dist_tpu.drivers.gssvx import LUFactorization
from superlu_dist_tpu.refine.ir import iterative_refinement

NX = int(os.environ.get("BENCH_NX", "48"))   # n = NX^3 = 110,592 default:
# large enough that the big separator fronts drive the MXU (the r1 bench at
# NX=24 was latency-bound, VERDICT weak #3), small enough that the Schur
# pool + fronts fit single-chip HBM with headroom
REPS = int(os.environ.get("BENCH_REPS", "5"))
DTYPE = "float32"
# v5e peak ~197 TFLOP/s bf16; f32 via HIGHEST-precision MXU passes ~1/4 of
# that.  MFU is reported against the f32 figure.
PEAK_F32 = float(os.environ.get("BENCH_PEAK_F32_TFLOPS", "49")) * 1e12
# TPU-tuned blocking: wide supernodes feed the MXU (SURVEY.md §7 step 10 —
# the reference's NSUP=128 is CPU-cache-sized) and keep the streamed
# executor's kernel count small.
RELAX, MAX_SUPER, MIN_BUCKET, GROWTH = 256, 1024, 64, 2.0


def _prepare():
    a = poisson3d(NX)
    opts = Options()
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(opts, a, sym)
    sf = symbolic_factorize(sym, col_order, relax=RELAX,
                            max_supernode=MAX_SUPER)
    plan = build_plan(sf, min_bucket=MIN_BUCKET, growth=GROWTH)
    avals = sym.data[sf.value_perm].astype(DTYPE)
    thresh = np.sqrt(np.finfo(DTYPE).eps) * a.norm_max()
    return a, sf, plan, avals, np.asarray(thresh, DTYPE)


def _time_factor(ex, avals, thresh, reps):
    out = jax.block_until_ready(ex(avals, thresh))     # warm (compile)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(ex(avals, thresh))
        times.append(time.perf_counter() - t0)
    if ex.last_profile:
        # kernel-shape trace (dgemm_mnk.dat analog) to stderr, top by time
        import sys
        top = sorted(ex.last_profile, key=lambda r: -r["seconds"])[:15]
        for r in top:
            print(f"# lvl={r['level']:<3d} B={r['batch']:<5d} m={r['m']:<5d} "
                  f"w={r['w']:<5d} u={r['u']:<5d} {r['seconds']*1e3:8.2f} ms "
                  f"{r['gflop']/max(r['seconds'],1e-12):8.1f} GF/s",
                  file=sys.stderr)
    return min(times), out


def main():
    a, sf, plan, avals_np, thresh_np = _prepare()

    backend = jax.default_backend()
    ex = StreamExecutor(plan, DTYPE)
    avals = jnp.asarray(avals_np)
    thresh = jnp.asarray(thresh_np)
    t_dev, (fronts, tiny) = _time_factor(ex, avals, thresh, REPS)
    gflops = plan.flops / t_dev / 1e9

    # Everything past this point (solve, residual, CPU baseline) must not
    # be able to zero the factor GFLOPS: each phase degrades independently
    # and the JSON line always prints (the pdtest harness likewise counts
    # failures and still reports, TEST/pdtest.c).
    residual = solve_path = None
    # residual through the full solve + f64 iterative refinement (GESP
    # semantics: f32 factors, refined solution; pdgsrfs.c:120) — via the
    # driver's own solve path (no equil/rowperm: identity transforms)
    try:
        numeric = NumericFactorization(plan=plan, fronts=list(fronts),
                                       tiny_pivots=int(tiny),
                                       dtype=jnp.dtype(DTYPE))
        n = a.n_rows
        ones = np.ones(n)
        ident = np.arange(n, dtype=np.int64)
        lu = LUFactorization(n=n, options=Options(), equed="N", dr=ones,
                             dc=ones, r1=ones, c1=ones, row_order=ident,
                             col_order=None, sf=sf, plan=plan,
                             numeric=numeric, a=a)
        xt = np.random.default_rng(0).standard_normal(n)
        b = a.matvec(xt)
        x, _ = iterative_refinement(a, b, lu.solve_factored(b),
                                    lu.solve_factored)
        residual = float(np.linalg.norm(b - a.matvec(x))
                         / max(np.linalg.norm(b), 1e-300))
        solve_path = ("device" if lu.solve_path == "auto"
                      and backend != "cpu" else "host")
        if lu.solve_path == "host" and backend != "cpu":
            solve_path = "host-fallback"
    except Exception as e:                   # pragma: no cover
        solve_path = f"failed: {type(e).__name__}: {e}"

    # Baseline: serial SuperLU (same code family as the reference) with
    # host CPU BLAS, factoring the identical matrix
    try:
        import scipy.sparse as sp
        from scipy.sparse.linalg import splu
        A = sp.csr_matrix((a.data, a.indices, a.indptr),
                          shape=(a.n_rows, a.n_rows)).tocsc()
        base_reps = 2 if a.n_rows < 50_000 else 1
        t_cpu = min(_timeit(lambda: splu(A)) for _ in range(base_reps))
        vs_baseline = round(t_cpu / t_dev, 2)
    except Exception:                        # pragma: no cover
        t_cpu = vs_baseline = None

    print(json.dumps({
        "metric": f"lu_factor_gflops_poisson3d_n{a.n_rows}_{DTYPE}",
        "value": round(gflops, 2),
        "unit": "GFLOP/s",
        "vs_baseline": vs_baseline,
        "backend": backend,
        "baseline": "scipy.splu (serial SuperLU, f64, host BLAS), same matrix",
        "baseline_seconds": t_cpu,
        "residual": residual,
        "solve_path": solve_path,
        "factor_seconds": t_dev,
        "flops": plan.flops,
        "mfu_pct": round(100.0 * gflops * 1e9 / PEAK_F32, 2),
        "n_kernels": ex.n_kernels,
        "n_groups": len(plan.groups),
        "tiny_pivots": int(tiny),
    }))


def _timeit(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
