"""Persistence tests (persist/ — docs/RELIABILITY.md).

Pins the crash-consistency contracts: a saved LU handle reloads and
solves with BITWISE-identical factors and no refactorization; factor
checkpoints resume to bitwise-identical L/U; corruption, truncation,
version drift and identity mismatch all answer with structured errors
(never garbage factors); and bundles round-trip across the int-width
(``SLU_TPU_INT64`` / INT alias) and precision (f64 / df64) configs.
"""

import hashlib
import os
import subprocess
import sys

import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson3d
from superlu_dist_tpu.utils.errors import (
    CheckpointCorruptError, CheckpointError, CheckpointMismatchError,
    CheckpointVersionError)
from superlu_dist_tpu.utils.options import Options

pytestmark = pytest.mark.persist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fronts_digest(fronts) -> str:
    h = hashlib.sha256()
    for lp, up in fronts:
        h.update(np.ascontiguousarray(np.asarray(lp)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(up)).tobytes())
    return h.hexdigest()


def _factored(nx=6, **opt_kw):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    a = poisson3d(nx)
    n = a.n_rows
    b = a.matvec(np.ones(n))
    x, lu, stats, info = gssvx(Options(**opt_kw), a, b)
    assert info == 0
    return a, b, lu


def _analyzed(nx=6, **kw):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    a = poisson3d(nx)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym), **kw)
    return a, build_plan(sf), sym.data[sf.value_perm]


# ---------------------------------------------------------------------------
# LU handle round trip
# ---------------------------------------------------------------------------

def test_lu_handle_round_trip_bitwise_and_solve(tmp_path):
    """Acceptance: a saved handle reloads and solves WITHOUT
    refactorization, with bitwise-identical factors."""
    from superlu_dist_tpu.persist import save_lu, load_lu
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.utils.options import Fact
    from superlu_dist_tpu.utils.stats import Stats
    import dataclasses

    a, b, lu = _factored()
    path = save_lu(lu, str(tmp_path / "handle"))
    lu2 = load_lu(path)

    assert _fronts_digest(lu2.numeric.fronts) == \
        _fronts_digest(lu.numeric.fronts)
    for (l1, u1), (l2, u2) in zip(lu.numeric.fronts, lu2.numeric.fronts):
        assert np.array_equal(np.asarray(l1), l2)
        assert np.array_equal(np.asarray(u1), u2)

    # direct solve through the reloaded handle
    x2 = lu2.solve_factored(b)
    resid = np.linalg.norm(b - a.matvec(x2)) / np.linalg.norm(b)
    assert resid < 1e-10

    # the full driver path: Fact=FACTORED never re-enters the
    # factorization (FACT time stays zero — no refactorization)
    stats = Stats()
    opts = dataclasses.replace(Options(), fact=Fact.FACTORED)
    x3, _, stats, info = gssvx(opts, a, b, lu=lu2, stats=stats)
    assert info == 0
    assert stats.utime["FACT"] == 0.0
    assert np.linalg.norm(b - a.matvec(x3)) / np.linalg.norm(b) < 1e-10


def test_manifest_is_versioned_and_digested(tmp_path):
    import json
    from superlu_dist_tpu.persist import save_lu, FORMAT_VERSION
    from superlu_dist_tpu.persist.serial import MANIFEST

    _, _, lu = _factored()
    path = save_lu(lu, str(tmp_path / "h"))
    doc = json.loads(open(os.path.join(path, MANIFEST)).read())
    assert doc["version"] == FORMAT_VERSION
    assert doc["kind"] == "lu_handle"
    assert doc["meta"]["n"] == lu.n
    # every artifact is digest-covered
    for name, ent in doc["arrays"].items():
        f = os.path.join(path, ent["file"])
        assert os.path.getsize(f) == ent["bytes"], name
        assert len(ent["sha256"]) == 64


def test_unknown_version_raises(tmp_path):
    import json
    from superlu_dist_tpu.persist import save_lu, load_lu
    from superlu_dist_tpu.persist.serial import MANIFEST

    _, _, lu = _factored()
    path = save_lu(lu, str(tmp_path / "h"))
    mpath = os.path.join(path, MANIFEST)
    doc = json.loads(open(mpath).read())
    doc["version"] = 999
    open(mpath, "w").write(json.dumps(doc))
    with pytest.raises(CheckpointVersionError):
        load_lu(path)


def test_corrupted_array_raises_structured(tmp_path):
    from superlu_dist_tpu.persist import save_lu, load_lu
    from superlu_dist_tpu.testing.chaos import corrupt_file

    _, _, lu = _factored()
    path = save_lu(lu, str(tmp_path / "h"))
    corrupt_file(os.path.join(path, "front_00000_l.npy"), mode="flip")
    with pytest.raises(CheckpointCorruptError, match="sha256"):
        load_lu(path)


def test_truncated_array_raises_structured(tmp_path):
    from superlu_dist_tpu.persist import save_lu, load_lu
    from superlu_dist_tpu.testing.chaos import corrupt_file

    _, _, lu = _factored()
    path = save_lu(lu, str(tmp_path / "h"))
    corrupt_file(os.path.join(path, "front_00000_u.npy"),
                 mode="truncate")
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        load_lu(path)


def test_corrupted_manifest_raises_structured(tmp_path):
    from superlu_dist_tpu.persist import save_lu, load_lu
    from superlu_dist_tpu.persist.serial import MANIFEST
    from superlu_dist_tpu.testing.chaos import corrupt_file

    _, _, lu = _factored()
    path = save_lu(lu, str(tmp_path / "h"))
    corrupt_file(os.path.join(path, MANIFEST), mode="truncate")
    with pytest.raises(CheckpointError):
        load_lu(path)


def test_missing_bundle_raises(tmp_path):
    from superlu_dist_tpu.persist import load_lu
    with pytest.raises(CheckpointError, match="MANIFEST"):
        load_lu(str(tmp_path / "nope"))


# ---------------------------------------------------------------------------
# factor checkpoint round trip / resume
# ---------------------------------------------------------------------------

def test_factor_checkpoint_resume_bitwise(tmp_path):
    """An interrupted-then-resumed factorization is bitwise identical to
    an uninterrupted one (the in-process twin of the kill -9 CI gate
    scripts/check_crash_resume.py)."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    a, plan, vals = _analyzed(nx=8)
    assert len(plan.groups) >= 4
    ref = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            executor="stream")
    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceededError) as ei:
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          ckpt_dir=ck,
                          deadline=CountdownDeadline(3))
    assert ei.value.checkpoint_path == os.path.abspath(ck)
    res = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            resume_from=ck)
    assert res.resumed_groups == 3
    assert _fronts_digest(res.fronts) == _fronts_digest(ref.fronts)
    assert res.tiny_pivots == ref.tiny_pivots


def test_resume_refuses_changed_values(tmp_path):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    a, plan, vals = _analyzed(nx=8)
    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          ckpt_dir=ck, deadline=CountdownDeadline(3))
    drifted = vals.copy()
    drifted[0] *= 1.5
    with pytest.raises(CheckpointMismatchError, match="different"):
        numeric_factorize(plan, drifted, a.norm_max(), dtype="float64",
                          resume_from=ck)


def test_resume_refuses_different_plan(tmp_path):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    a, plan, vals = _analyzed(nx=8)
    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          ckpt_dir=ck, deadline=CountdownDeadline(3))
    # a different blocking config yields a different plan fingerprint
    _, plan2, vals2 = _analyzed(nx=8, relax=4, max_supernode=16)
    with pytest.raises(CheckpointMismatchError, match="different"):
        numeric_factorize(plan2, vals2, a.norm_max(), dtype="float64",
                          resume_from=ck)


def test_resume_recorded_as_solve_report_rung(tmp_path):
    """gssvx(resume_from=...) records the resume on stats.resume AND as
    a 'resume-from-checkpoint' rung in the SolveReport ladder."""
    from superlu_dist_tpu.drivers.gssvx import analyze, gssvx
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    # the checkpoint must belong to the DRIVER's analysis (equil + MC64
    # + its column order), so write it from analyze()'s own products —
    # the driver's re-analysis is deterministic, so the fingerprints
    # line up on resume
    a = poisson3d(8)
    lu0, bvals, _ = analyze(Options(), a)
    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(lu0.plan, bvals, lu0.anorm, dtype="float64",
                          ckpt_dir=ck, deadline=CountdownDeadline(3))
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = gssvx(Options(), a, b, resume_from=ck)
    assert info == 0
    assert stats.resume["groups"] == 3
    rep = stats.solve_report
    rungs = [r for r in rep.rungs if r.name == "resume-from-checkpoint"]
    assert len(rungs) == 1
    assert "3/" in rungs[0].detail
    assert "resume-from-checkpoint" in rep.summary()
    assert "resumed" in stats.report()
    resid = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
    assert resid < 1e-10


def test_checkpoint_removed_after_completed_run(tmp_path):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    a, plan, vals = _analyzed(nx=6)
    ck = str(tmp_path / "ck")
    numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                      ckpt_dir=ck, ckpt_every=2)
    # a completed factorization leaves no stale frontier behind
    assert not os.path.exists(os.path.join(ck, "MANIFEST.json"))


# ---------------------------------------------------------------------------
# cross-config round trips (int width, precision)
# ---------------------------------------------------------------------------

_WORKER = r"""
import hashlib, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
from superlu_dist_tpu.models.gallery import poisson3d
from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.persist import save_lu, load_lu

def digest(fronts):
    h = hashlib.sha256()
    for lp, up in fronts:
        h.update(np.ascontiguousarray(np.asarray(lp)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(up)).tobytes())
    return h.hexdigest()

mode, path = sys.argv[1], sys.argv[2]
a = poisson3d(6)
b = a.matvec(np.ones(a.n_rows))
if mode == "save":
    x, lu, stats, info = gssvx(Options(), a, b)
    assert info == 0
    save_lu(lu, path)
    print("DIGEST", digest(lu.numeric.fronts))
else:
    lu = load_lu(path)
    x = lu.solve_factored(b)
    resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    assert resid < 1e-10, resid
    print("DIGEST", digest(lu.numeric.fronts))
"""


def _run_worker(mode, path, int64: bool):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLU_TPU_INT64="1" if int64 else "0")
    r = subprocess.run(
        [sys.executable, "-c", _WORKER.format(repo=REPO), mode, path],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr
    for line in r.stdout.splitlines():
        if line.startswith("DIGEST "):
            return line.split()[1]
    raise AssertionError(f"no digest in worker output: {r.stdout}")


@pytest.mark.parametrize("save64,load64", [(False, True), (True, False)])
def test_round_trip_across_int_width_configs(tmp_path, save64, load64):
    """A handle saved under one SLU_TPU_INT64 (INT alias) config loads
    under the other with bitwise-identical L/U and a working solve."""
    path = str(tmp_path / "h")
    d_save = _run_worker("save", path, int64=save64)
    d_load = _run_worker("load", path, int64=load64)
    assert d_save == d_load


def test_round_trip_df64_config(tmp_path):
    """df64 (emulated-double) factors — recombined host f64 — round-trip
    bitwise through the same bundle format."""
    from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric
    from superlu_dist_tpu.persist import save_lu, load_lu
    import dataclasses

    a = poisson3d(5)
    opts = dataclasses.replace(Options(), factor_dtype="df64")
    lu, bvals, stats = analyze(opts, a)
    info = factorize_numeric(lu, bvals, stats)
    assert info == 0
    assert str(lu.numeric.dtype) == "float64"   # recombined exact f64
    path = save_lu(lu, str(tmp_path / "h"))
    lu2 = load_lu(path)
    assert _fronts_digest(lu2.numeric.fronts) == \
        _fronts_digest(lu.numeric.fronts)


def test_round_trip_f32_dtype(tmp_path):
    from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric
    from superlu_dist_tpu.persist import save_lu, load_lu
    import dataclasses

    a = poisson3d(5)
    opts = dataclasses.replace(Options(), factor_dtype="float32")
    lu, bvals, stats = analyze(opts, a)
    assert factorize_numeric(lu, bvals, stats) == 0
    path = save_lu(lu, str(tmp_path / "h"))
    lu2 = load_lu(path)
    assert str(np.dtype(lu2.numeric.dtype)) == "float32"
    assert lu2.numeric.fronts[0][0].dtype == np.float32
    assert _fronts_digest(lu2.numeric.fronts) == \
        _fronts_digest(lu.numeric.fronts)
