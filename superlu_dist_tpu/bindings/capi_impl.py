"""Implementation of the slu_tpu C API (slu_tpu.h / slu_tpu_capi.c).

The C shim embeds a Python interpreter, imports this module, and calls
these functions with raw pointers (as int64) into the caller's buffers —
the role the reference's handle-based wrapper layer plays for its
Fortran interface (FORTRAN/superlu_c2f_dwrap.c:51-327): a registry of
live factorizations plus option and statistics marshalling.

Surface map to the reference wrapper:
  opt_create/opt_set/opt_get/opt_free   <-> f_create_options /
      f_set_default_options / set_superlu_options (c2f_dwrap options block)
  factor_opts / refactor                <-> f_pdgssvx with Fact=DOFACT /
      SamePattern / SamePattern_SameRowPerm (fact_t tiers,
      superlu_defs.h:489-510)
  solve_factored_opts                   <-> f_pdgssvx with Fact=FACTORED
      (trans/refine ride the options handle)
  stat_get                              <-> f_PStatPrint-class observability
      (per-phase seconds, flops, tiny pivots, memory; SRC/util.c:484-534)

B/X are column-major (ldb/ldx leading dimensions, n x nrhs) as a Fortran
caller lays them out (the reference pdgssvx's ldb contract).
"""

from __future__ import annotations

import ctypes
import dataclasses
import math

import numpy as np

import superlu_dist_tpu as _slu
from superlu_dist_tpu.sparse.formats import SparseCSR as _CSR

_handles: dict[int, dict] = {}
_options: dict[int, object] = {}
_next = [1]

_BAD_HANDLE = -3
_BAD_KEY = -5
_BAD_VALUE = -6

# reference-style option names (superlu_dist_options_t fields,
# superlu_defs.h:628-657) -> Options dataclass fields; native field
# names are accepted too
_KEY_ALIAS = {
    "Fact": "fact", "Equil": "equil", "ColPerm": "col_perm",
    "RowPerm": "row_perm", "ReplaceTinyPivot": "replace_tiny_pivot",
    "IterRefine": "iter_refine", "Trans": "trans", "DiagInv": "diag_inv",
    "PrintStat": "print_stat", "ParSymbFact": "par_symb_fact",
}
_ENUM_FIELDS = {
    "fact": _slu.Fact, "col_perm": _slu.ColPerm, "row_perm": _slu.RowPerm,
    "iter_refine": _slu.IterRefine, "trans": _slu.Trans,
}


def _as(ptr, n, ct):
    return np.ctypeslib.as_array(
        ctypes.cast(int(ptr), ctypes.POINTER(ct)), (int(n),))


def _mat(n, nnz, ip, ix, vp):
    indptr = _as(ip, n + 1, ctypes.c_int64).copy()
    indices = _as(ix, nnz, ctypes.c_int64).copy()
    values = _as(vp, nnz, ctypes.c_double).copy()
    return _CSR(n, n, indptr, indices, values)


def _rhs(bp, n, nrhs, ldb=None):
    ldb = n if ldb in (None, 0) else ldb
    if ldb < n:
        return None
    b = _as(bp, ldb * nrhs, ctypes.c_double).copy() \
        .reshape(ldb, nrhs, order="F")[:n]
    return b[:, 0] if nrhs == 1 else b


def _writeback(xp, x, n, nrhs, ldx=None):
    ldx = n if ldx in (None, 0) else ldx
    out = _as(xp, ldx * nrhs, ctypes.c_double).reshape(ldx, nrhs, order="F")
    out[:n] = np.asarray(x).reshape(n, nrhs)


def _opts_for(opt_handle):
    """Options instance for a handle (0 = fresh defaults; None if bad)."""
    if opt_handle == 0:
        return _slu.Options()
    return _options.get(opt_handle)


# ---- options registry -------------------------------------------------------

def opt_create():
    h = _next[0]
    _next[0] += 1
    _options[h] = _slu.Options()
    return h


def opt_free(h):
    return 0 if _options.pop(h, None) is not None else _BAD_HANDLE


def _coerce(field_type, cur, val):
    """Parse the C caller's string value for an Options field."""
    if field_type is not None:            # enum field
        if val.lstrip("-").isdigit():
            return field_type(int(val))
        for m in field_type:
            if m.name.upper() == val.upper():
                return m
        raise ValueError(val)
    if isinstance(cur, bool):
        u = val.strip().upper()
        if u in ("YES", "TRUE", "1", "ON"):
            return True
        if u in ("NO", "FALSE", "0", "OFF"):
            return False
        raise ValueError(val)
    if isinstance(cur, int):
        return int(val)
    if isinstance(cur, float):
        return float(val)
    return val                            # str field (factor_dtype, ...)


def opt_set(h, key, val):
    opts = _options.get(h)
    if opts is None:
        return _BAD_HANDLE
    name = _KEY_ALIAS.get(key, key)
    if not hasattr(opts, name):
        return _BAD_KEY
    try:
        setattr(opts, name, _coerce(_ENUM_FIELDS.get(name),
                                    getattr(opts, name), val))
    except (ValueError, TypeError):
        return _BAD_VALUE
    return 0


def opt_get(h, key):
    """Value string, or an int error code (-3 bad handle / -5 bad key —
    the C shim distinguishes PyLong from PyUnicode returns)."""
    opts = _options.get(h)
    if opts is None:
        return _BAD_HANDLE
    name = _KEY_ALIAS.get(key, key)
    if not hasattr(opts, name):
        return _BAD_KEY
    v = getattr(opts, name)
    return v.name if hasattr(v, "name") else \
        ("YES" if v is True else "NO" if v is False else str(v))


# ---- solve / factor ---------------------------------------------------------

def solve_opts(opt, n, nnz, ip, ix, vp, bp, ldb, xp, ldx, nrhs):
    opts = _opts_for(opt)
    b = _rhs(bp, n, nrhs, ldb)
    if opts is None or b is None or (ldx not in (None, 0) and ldx < n):
        return _BAD_HANDLE if opts is None else _BAD_VALUE
    a = _mat(n, nnz, ip, ix, vp)
    x, lu, stats, info = _slu.gssvx(opts, a, b)
    if info == 0:
        _writeback(xp, x, n, nrhs, ldx)
    return int(info)


def factor_opts(opt, n, nnz, ip, ix, vp):
    from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric
    opts = _opts_for(opt)
    if opts is None:
        return (_BAD_HANDLE, 0)
    a = _mat(n, nnz, ip, ix, vp)
    # factor WITHOUT a solve (the analyze + factorize_numeric split):
    # no wasted zero-RHS triangular solve, and on an accelerator no
    # device-solve program is compiled before a solve is requested
    lu, bvals, stats = analyze(opts, a)
    info = factorize_numeric(lu, bvals, stats)
    if info != 0:
        return (int(info), 0)
    h = _next[0]
    _next[0] += 1
    # snapshot the options: later opt_set calls on the caller's options
    # handle must not retroactively change this factorization's stored
    # solve/refactor semantics ("the handle's own options", slu_tpu.h)
    _handles[h] = {"a": a, "lu": lu, "stats": stats,
                   "opts": dataclasses.replace(opts)}
    return (0, h)


def refactor(h, nnz, vp, tier):
    """Refactor with NEW numeric values on the SAME pattern, through the
    reference's reuse tiers: tier 1 = SamePattern (column order +
    detected-equal row perms reuse the symbolic/plan), tier 2 =
    SamePattern_SameRowPerm (scalings + row perm + symbolic + plan all
    reused; numeric factorization only)."""
    ent = _handles.get(h)
    if ent is None:
        return _BAD_HANDLE
    a0 = ent["a"]
    if nnz != a0.nnz:
        return _BAD_VALUE
    fact = {1: _slu.Fact.SamePattern,
            2: _slu.Fact.SamePattern_SameRowPerm}.get(tier)
    if fact is None:
        return _BAD_VALUE
    a = _CSR(a0.n_rows, a0.n_cols, a0.indptr, a0.indices,
             _as(vp, nnz, ctypes.c_double).copy())
    from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric
    opts = dataclasses.replace(ent["opts"], fact=fact)
    lu, bvals, stats = analyze(opts, a, lu=ent["lu"], stats=ent["stats"])
    info = factorize_numeric(lu, bvals, stats)
    if info != 0:
        return int(info)
    ent.update(a=a, lu=lu, stats=stats)
    return 0


def solve_factored_opts(h, opt, n, bp, ldb, xp, ldx, nrhs):
    ent = _handles.get(h)
    if ent is None:
        return _BAD_HANDLE
    opts = ent["opts"] if opt == 0 else _opts_for(opt)
    b = _rhs(bp, n, nrhs, ldb)
    if opts is None or b is None or (ldx not in (None, 0) and ldx < n):
        return _BAD_HANDLE if opts is None else _BAD_VALUE
    opts = dataclasses.replace(opts, fact=_slu.Fact.FACTORED)
    x, lu, stats, info = _slu.gssvx(opts, ent["a"], b, lu=ent["lu"],
                                    stats=ent["stats"])
    if info == 0:
        _writeback(xp, x, n, nrhs, ldx)
    return int(info)


def free(h):
    return 0 if _handles.pop(h, None) is not None else _BAD_HANDLE


# ---- statistics (PStatPrint-class observability) ----------------------------

def stat_get(h, name):
    """A named statistic of a factorization handle as float, or an int
    error code (-3 bad handle; unknown names yield NaN, which the C shim
    maps to -5)."""
    ent = _handles.get(h)
    if ent is None:
        return _BAD_HANDLE
    st = ent["stats"]
    lu = ent["lu"]
    name_u = name.upper()
    if name_u in st.utime:
        return float(st.utime[name_u])
    special = {
        "TINY_PIVOTS": float(st.tiny_pivots),
        "REFINE_STEPS": float(st.refine_steps),
        "FACT_FLOPS": float(st.ops.get("FACT", 0.0)),
        "FACT_GFLOPS": float(st.gflops("FACT")),
        "LU_BYTES": float(st.for_lu_bytes),
        "TOTAL_BYTES": float(st.peak_memory_bytes),
        "BERR": float(max(lu.berrs)) if lu.berrs else 0.0,
        "NNZ_L": float(lu.sf.nnz_L) if lu.sf is not None else math.nan,
        "NNZ_U": float(lu.sf.nnz_U) if lu.sf is not None else math.nan,
    }
    return special.get(name_u, math.nan)


# ---- legacy narrow surface (kept ABI-stable) --------------------------------

def solve(n, nnz, ip, ix, vp, bp, xp, nrhs):
    return solve_opts(0, n, nnz, ip, ix, vp, bp, n, xp, n, nrhs)


def factor(n, nnz, ip, ix, vp):
    return factor_opts(0, n, nnz, ip, ix, vp)


def solve_factored(h, n, bp, xp, nrhs):
    return solve_factored_opts(h, 0, n, bp, n, xp, n, nrhs)
