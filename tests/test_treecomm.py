"""Tree broadcast/reduction engine (TreeBcast_slu / TreeReduce_slu analog).

Multi-process tests: real processes coordinate through the shared-memory
segment, mirroring how the reference tests multi-node behavior by
oversubscribing ranks on one box (SURVEY.md §4, .travis_tests.sh).
Covers both topologies: flat (n <= 8) and binary (n > 8,
TreeBcast_slu.hpp:17-29).
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _worker(name, n_ranks, rank, root, q):
    # import inside the child: must not inherit initialized JAX state
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    with TreeComm(name, n_ranks, rank, max_len=64,
                  create=False) as tc:
        # 1) bcast: root sends its rank-stamped payload
        buf = np.full(8, float(rank))
        tc.bcast(buf, root=root)
        bcast_ok = bool((buf == float(root)).all())
        # 2) reduce: everyone contributes rank+1
        buf2 = np.full(8, float(rank + 1))
        tc.reduce_sum(buf2, root=root)
        # 3) a second round immediately (slot-reuse path)
        buf3 = np.full(8, 1.0)
        tc.allreduce_sum(buf3, root=root)
        q.put((rank, bcast_ok, float(buf2[0]), float(buf3[0])))


def _run(n_ranks, root):
    name = f"/slu_tree_test_{os.getpid()}_{n_ranks}_{root}"
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    owner = TreeComm(name, n_ranks, 0, max_len=64, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, n_ranks, r, root, q))
                 for r in range(1, n_ranks)]
        for p in procs:
            p.start()
        # rank 0 participates from this process
        buf = np.full(8, 0.0)
        owner.bcast(buf, root=root)
        buf2 = np.full(8, 1.0)
        owner.reduce_sum(buf2, root=root)
        buf3 = np.full(8, 1.0)
        owner.allreduce_sum(buf3, root=root)
        results = {0: (0, bool((buf == float(root)).all()),
                       float(buf2[0]), float(buf3[0]))}
        for _ in procs:
            r = q.get(timeout=60)
            results[r[0]] = r
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    total = n_ranks * (n_ranks + 1) / 2.0   # sum of rank+1
    for rank, (rk, bcast_ok, red, allred) in results.items():
        assert bcast_ok, f"rank {rank} bcast payload wrong"
        if rank == root:
            assert red == total, (rank, red, total)
        assert allred == float(n_ranks), (rank, allred)


def test_flat_tree_6_ranks():
    _run(6, root=0)


def test_flat_tree_nonzero_root():
    _run(5, root=3)


def test_binary_tree_12_ranks():
    _run(12, root=0)


def test_binary_tree_nonzero_root():
    _run(10, root=7)


def _obj_payload():
    return {
        "blob": b"\x00\xff analysis \x01" * 7,        # odd length, NULs
        "big_ints": np.array([2**62 + 3, -(2**55) - 1], dtype=np.int64),
        "nan_bits": np.array([np.nan, -0.0, np.inf]),
        "sf_like": {"sn_rows": [np.arange(5), np.arange(3) * 7]},
    }


def _obj_worker(name, n_ranks, rank, root, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    with TreeComm(name, n_ranks, rank, max_len=16, create=False) as tc:
        got = tc.bcast_obj(_obj_payload() if rank == root else None,
                           root=root)
        ref = _obj_payload()
        ok = (got["blob"] == ref["blob"]
              and np.array_equal(got["big_ints"], ref["big_ints"])
              and np.array_equal(got["nan_bits"], ref["nan_bits"],
                                 equal_nan=True)
              and all(np.array_equal(a, b) for a, b in
                      zip(got["sf_like"]["sn_rows"],
                          ref["sf_like"]["sn_rows"])))
        q.put((rank, ok))


def test_bcast_obj_bit_exact_chunked():
    """Pickled-object broadcast (the mesh tier's analysis transport):
    bytes ride the f64 slots bit-exactly — int64 beyond 2^53 and NaN
    payloads must survive, which the mantissa ride could not carry —
    and max_len=16 forces the chunked streaming path."""
    name = f"/slu_tree_obj_{os.getpid()}"
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    n_ranks, root = 4, 1
    owner = TreeComm(name, n_ranks, 0, max_len=16, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_obj_worker,
                             args=(name, n_ranks, r, root, q))
                 for r in range(1, n_ranks)]
        for p in procs:
            p.start()
        got = owner.bcast_obj(None, root=root)
        assert got["blob"] == _obj_payload()["blob"]
        assert np.array_equal(got["big_ints"], _obj_payload()["big_ints"])
        for _ in procs:
            rank, ok = q.get(timeout=60)
            assert ok, f"rank {rank} payload mismatch"
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)


def test_single_rank_noop():
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    name = f"/slu_tree_solo_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=16, create=True) as tc:
        b = np.arange(4.0)
        tc.bcast(b)
        tc.reduce_sum(b)
        np.testing.assert_array_equal(b, np.arange(4.0))


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
