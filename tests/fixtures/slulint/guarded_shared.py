"""SLU108 clean negative: every cross-thread touch of self._count
holds the owning lock; immutable-after-init state (self._interval) is
read freely."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._count = 0
        self._interval = 0.01
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            with self._lock:
                self._bump_locked()

    def _bump_locked(self):
        self._count += 1

    def stats(self):
        with self._lock:
            return self._count

    def close(self):
        self._stop.set()
        self._thread.join(1.0)
