"""Serving fleet — health-checked routing over multi-handle replicas.

ROADMAP item 4, the tier ABOVE :class:`~superlu_dist_tpu.serve.server.
SolveServer`: one server owns one factored handle in one process; real
traffic is many matrices (per-user/per-model systems), rolling
refactorizations, and more QPS than one host.  This module composes the
pieces the reliability era already built into that fleet:

* **multi-handle replicas** — each replica owns a
  :class:`~superlu_dist_tpu.serve.handlecache.HandleCache` (LRU of
  factored handles loaded zero-refactor from sha256-manifested persist
  bundles, byte-budgeted via the ``lu_meta`` cheap peek, scrub-verified
  on every load), so ONE replica serves a mixed stream of matrices
  keyed by the caller's bundle key.  Replicas come in two isolations
  behind the same interface: in-process worker threads
  (:class:`ThreadReplica`) and spawned worker processes
  (:class:`ProcessReplica`, the kill -9 failure domain).
* **health-checked routing** — :class:`FleetRouter` fans
  ``submit(key, b)`` across N replicas (handle-affinity first, then
  least-loaded), with replica health judged by the PR 8 failure
  detector's verdict generalized to replica processes:
  ``parallel.treecomm.pid_alive`` (kill(pid,0) + zombie state) for
  process replicas, worker-thread liveness for thread replicas — a
  SLOW replica is never declared failed (the slow-not-dead
  discipline), a quarantined one is routed around but never killed.
* **fleet backpressure** — the PR 10 admission verbs lifted one level:
  ``SLU_TPU_FLEET_QUEUE_MAX`` sheds at the router (reason
  ``fleet_queue_full``) before any replica queues the work, and
  ``SLU_TPU_FLEET_DEADLINE_MS`` arms END-TO-END per-ticket deadlines
  (queued, in flight, or mid-failover — the health monitor and the
  waiting ticket both expire it).
* **zero-loss failover** — every accepted ticket carries an idempotent
  retry token; when a replica dies (pid gone, pipe closed, worker
  crashed) or quarantines, the router re-routes every ticket that
  replica had accepted but not delivered to a healthy replica under
  the SAME token (first delivery wins, duplicates are dropped), so the
  client observes bitwise-identical X and never an error while a
  healthy replica remains.  The failover dumps a flight-recorder
  postmortem (:class:`ReplicaFailureError` construction) naming the
  dead replica and the re-routed ticket set.
* **rolling deploy** — :meth:`FleetRouter.deploy` drives per-replica
  ``SolveServer.swap`` one replica at a time (the swap IS the
  drain/resume point: queued + future tickets on the new handle, the
  in-flight batch finishes on the old one — zero dropped), gating each
  replica behind the new bundle's load/scrub integrity verification
  and a canary batch (finiteness + optional componentwise-BERR gate);
  any failure rolls every already-swapped replica back to the previous
  bundle and raises :class:`DeployRollbackError`.

Determinism contract: a replica serves each accepted ticket as its OWN
micro-batch (the worker is serialized, and the fleet's default server
keywords disable the coalescing window).  Batch composition is part of
the arithmetic — the nrhs width selects the padded bucket — so
one-ticket-one-batch is what makes a re-routed ticket's X **bitwise
identical** to the undisturbed run, which is the property the
``fleet-failover`` CI gate pins.  Cross-replica concurrency, not
cross-request coalescing, is the fleet's throughput axis.

Metrics (obs/metrics.py): ``slu_fleet_replicas_healthy`` gauge,
``slu_fleet_{requests,columns,reroutes,failovers,deploys,rollbacks,
handle_evictions}_total`` counters and the ``slu_fleet_route_seconds``
submit→delivery histogram.  Chaos specs ``kill_replica=R@batch=K``,
``quarantine_replica=R`` and ``slow_replica=R,secs=S``
(testing/chaos.py) drive the failure domains deterministically;
docs/SERVING.md's fleet chapter has the failure-domain matrix.
"""

from __future__ import annotations

import multiprocessing
import os
import signal
import threading
import time

import numpy as np

from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.slo import (NULL_TICKET, SLOEvaluator,
                                      TicketContext, get_accounter,
                                      parent_ref)
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.parallel.treecomm import pid_alive
from superlu_dist_tpu.serve.handlecache import HandleCache
from superlu_dist_tpu.utils.errors import (
    CheckpointError, DeployRollbackError, FactorCorruptError,
    RefactorRollbackError, ReplicaFailureError, ServeDeadlineError,
    ServeOverloadError, ServerClosedError, SuperLUError)
from superlu_dist_tpu.utils.lockwatch import make_condition, make_lock

#: default SolveServer keywords for fleet-loaded handles: no coalescing
#: window — one accepted ticket, one micro-batch (the determinism
#: contract in the module docstring)
FLEET_SERVER_KW = {"max_wait_s": 0.0}


class _RemoteServeError(SuperLUError):
    """A process replica's per-ticket serve error, re-raised in the
    router process.  Structured errors do not round-trip a pickle
    faithfully (their constructors take positional evidence), so the
    child ships ``(type name, message)`` and the router wraps them —
    ``remote_type`` keeps the verdict inspectable."""

    def __init__(self, remote_type: str, message: str, replica: int):
        self.remote_type = remote_type
        self.replica = int(replica)
        super().__init__(
            f"replica {replica} served a structured error "
            f"({remote_type}): {message}")


class _TicketRec:
    """Router-side record of one accepted ticket (the idempotent retry
    token is ``token``; delivery is first-wins)."""

    __slots__ = ("token", "key", "b", "k", "squeeze", "t_submit",
                 "deadline_s", "t_deadline", "event", "error", "x",
                 "replica", "tried", "attempts", "ctx", "t_routed")

    def __init__(self, token: int, key, b: np.ndarray, squeeze: bool):
        self.token = token
        self.key = key
        self.b = b
        self.k = b.shape[1]
        self.squeeze = squeeze
        self.ctx = NULL_TICKET   # TicketContext when tracing is on
        self.t_submit = time.perf_counter()
        self.t_routed = self.t_submit   # last route/reroute stage edge
        self.deadline_s = 0.0
        self.t_deadline = None
        self.event = threading.Event()
        self.error = None
        self.x = None
        self.replica = -1
        self.tried = set()
        self.attempts = 0


class FleetTicket:
    """Future-style handle for one fleet submit.  ``result()`` returns
    the solved X (or raises the ticket's structured error); a replica
    death between submit and delivery is INVISIBLE here — the router
    re-routes under the same token and the X that arrives is bitwise
    identical to an undisturbed run."""

    def __init__(self, rec: _TicketRec, router: "FleetRouter"):
        self._rec = rec
        self._router = router

    @property
    def token(self) -> int:
        """The idempotent retry token this ticket travels under."""
        return self._rec.token

    def done(self) -> bool:
        return self._rec.event.is_set()

    @property
    def attempts(self) -> int:
        """Routing attempts so far (1 = never re-routed)."""
        return self._rec.attempts

    def result(self, timeout: float | None = None) -> np.ndarray:
        rec = self._rec
        end = None if timeout is None else time.perf_counter() + timeout
        while not rec.event.is_set():
            now = time.perf_counter()
            if end is not None and now >= end:
                raise TimeoutError(
                    f"fleet ticket {rec.token} ({rec.k} columns, key "
                    f"{rec.key!r}) not delivered within {timeout}s")
            bounds = [] if end is None else [end - now]
            if rec.t_deadline is not None:
                if now >= rec.t_deadline:
                    # end-to-end deadline: expire it ourselves when the
                    # monitor has not yet (no-op if delivery raced us)
                    self._router._expire(rec, now)
                    bounds = [0.05] + bounds
                else:
                    bounds.append(rec.t_deadline - now)
            rec.event.wait(min(bounds) if bounds else 0.5)
        if rec.error is not None:
            raise rec.error
        x = rec.x
        return x[:, 0] if rec.squeeze else x


# ---------------------------------------------------------------------------
# replicas
# ---------------------------------------------------------------------------

class ThreadReplica:
    """In-process replica: one serialized worker thread over a private
    :class:`HandleCache`.  The worker serves one accepted ticket per
    micro-batch (determinism contract) and runs deploy/canary commands
    in-band — BETWEEN batches, which is the per-replica drain point the
    rolling deploy relies on."""

    kind = "thread"

    def __init__(self, rid: int, router: "FleetRouter", paths: dict,
                 server_kw: dict, handle_bytes: int | None):
        from superlu_dist_tpu.testing.chaos import get_fleet_chaos
        self.rid = int(rid)
        self._router = router
        self._cache = HandleCache(handle_bytes, server_kw)
        for key, path in paths.items():
            self._cache.register(key, path)
        self._lock = make_lock(f"ThreadReplica[{rid}]._lock")
        self._cond = make_condition(f"ThreadReplica[{rid}]._cond",
                                    self._lock)
        self._work: list = []
        self._closed = False
        self._dead = False
        self._quarantined = False
        self._batches = 0
        self._chaos = get_fleet_chaos()   # per-replica monkey state
        self._thread = threading.Thread(
            target=self._worker, name=f"slu-fleet-replica-{rid}",
            daemon=True)
        self._thread.start()

    # -- interface ------------------------------------------------------
    def submit(self, rec: _TicketRec) -> bool:
        with self._cond:
            if self._closed or self._dead or self._quarantined:
                return False
            self._work.append(("serve", rec))
            self._cond.notify_all()
        return True

    def register(self, key, path: str) -> None:
        self._cache.register(key, path)

    def deploy(self, key, path: str) -> bool:
        """Hot-swap ``key`` to ``path`` in-band (between batches);
        returns True when a resident handle was actually swapped."""
        return self._run_cmd(lambda: self._cache.deploy(key, path))

    def canary(self, key, b: np.ndarray) -> np.ndarray:
        """Serve one canary batch through THIS replica, in-band."""
        return self._run_cmd(
            lambda: np.asarray(self._cache.get(key).solve(b, 120.0)))

    def alive(self) -> bool:
        """The liveness verdict (thread analog of ``pid_alive``): the
        worker thread runs and has not simulated a crash.  Slowness is
        never death."""
        with self._lock:
            if self._dead:
                return False
            return self._thread.is_alive() or self._closed

    def routable(self) -> bool:
        with self._lock:
            return not (self._closed or self._dead or self._quarantined)

    def affinity(self, key) -> bool:
        return key in self._cache.resident()

    def describe(self) -> dict:
        with self._lock:
            return {"rid": self.rid, "kind": self.kind,
                    "batches": self._batches, "dead": self._dead,
                    "quarantined": self._quarantined,
                    "cache": self._cache.stats()}

    def close(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        self._cache.close()

    # -- worker ---------------------------------------------------------
    def _run_cmd(self, fn, timeout: float = 120.0):
        box = {"ok": None, "val": None}
        done = threading.Event()

        def run():
            try:
                box["val"] = fn()
                box["ok"] = True
            except Exception as e:          # noqa: BLE001 — travels back
                box["val"] = e
                box["ok"] = False
            done.set()

        with self._cond:
            if self._closed or self._dead:
                raise SuperLUError(
                    f"fleet replica {self.rid} is not accepting "
                    "commands (closed or failed)")
            self._work.append(("cmd", run))
            self._cond.notify_all()
        if not done.wait(timeout):
            raise SuperLUError(
                f"fleet replica {self.rid} command timed out "
                f"({timeout}s)")
        if not box["ok"]:
            raise box["val"]
        return box["val"]

    def _worker(self):
        while True:
            with self._cond:
                while not self._work and not self._closed:
                    self._cond.wait(0.1)
                if self._closed and not self._work:
                    return
                if self._dead:
                    return
                kind, item = self._work.pop(0)
            if kind == "cmd":
                item()
                continue
            if self._serve_one(item) is False:
                return                      # simulated crash

    def _serve_one(self, rec: _TicketRec):
        rec_live = not rec.event.is_set() and rec.replica == self.rid
        if not rec_live:
            return None     # re-routed or expired while queued here
        chaos = self._chaos
        if chaos is not None:
            stall = chaos.replica_stall_s(self.rid)
            if stall > 0:
                time.sleep(stall)           # slow, NOT dead
            if chaos.replica_quarantined(self.rid):
                self._mark_quarantined()
                self._router._replica_unroutable(
                    self.rid, "chaos quarantine_replica")
                return None
            with self._lock:
                batches = self._batches
            if chaos.replica_kill_due(self.rid, batches):
                # the thread-replica analog of kill -9: stop serving
                # with every accepted ticket undelivered — the router
                # must re-route them all
                with self._lock:
                    self._dead = True
                self._router._replica_failed(
                    self.rid,
                    cause="chaos kill_replica (simulated SIGKILL)")
                return False
        try:
            srv = self._cache.get(rec.key)
            # same-process replica: the router-minted context IS the
            # parent, so the server's stage spans share its trace id
            t = srv.submit(rec.b,
                           parent=rec.ctx if rec.ctx.enabled else None)
            srv.flush()
            x = None
            while x is None:
                try:
                    x = np.asarray(t.result(timeout=1.0))
                except TimeoutError:
                    with self._lock:
                        gone = self._closed or self._dead
                    if gone:
                        return None     # close/crash purge handles rec
            with self._lock:
                self._batches += 1
            self._router._deliver(rec, x=x, rid=self.rid)
        except (FactorCorruptError, CheckpointError,
                ServerClosedError) as e:
            # handle-level failure: the replica (not the ticket) is the
            # blast radius — quarantine and let the router re-route
            self._mark_quarantined()
            self._router._replica_unroutable(
                self.rid, f"{type(e).__name__}: {e}")
        except Exception as e:              # noqa: BLE001 — per-ticket
            self._router._deliver(rec, err=e, rid=self.rid)
        return None

    def _mark_quarantined(self):
        with self._lock:
            self._quarantined = True


def _replica_child_main(conn, rid: int, paths: dict, server_kw: dict,
                        handle_bytes: int | None):
    """Process-replica worker: a fresh (spawned) interpreter serving a
    private HandleCache over a pipe.  One message, one micro-batch —
    the same determinism contract as the thread replica.  Chaos
    ``kill_replica`` here is a REAL ``kill -9`` of this process."""
    from superlu_dist_tpu.testing.chaos import get_fleet_chaos
    cache = HandleCache(handle_bytes, server_kw)
    for key, path in paths.items():
        cache.register(key, path)
    chaos = get_fleet_chaos()
    batches = 0
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            tag = msg[0]
            if tag == "close":
                break
            if tag == "register":
                _, key, path = msg
                try:
                    cache.register(key, path)
                except Exception:           # noqa: BLE001 — best effort
                    pass
                continue
            if tag == "deploy":
                _, seq, key, path = msg
                try:
                    swapped = cache.deploy(key, path)
                    conn.send(("cmd", seq, True, swapped))
                except Exception as e:      # noqa: BLE001 — travels back
                    conn.send(("cmd", seq, False,
                               f"{type(e).__name__}: {e}"))
                continue
            if tag == "canary":
                _, seq, key, b = msg
                try:
                    x = np.asarray(cache.get(key).solve(b, 120.0))
                    conn.send(("cmd", seq, True, x))
                except Exception as e:      # noqa: BLE001 — travels back
                    conn.send(("cmd", seq, False,
                               f"{type(e).__name__}: {e}"))
                continue
            if tag == "metrics_pull":
                _, seq = msg
                try:
                    from superlu_dist_tpu.obs.metrics import get_metrics
                    m = get_metrics()
                    conn.send(("cmd", seq, True,
                               m.snapshot() if m.enabled else None))
                except Exception as e:      # noqa: BLE001 — travels back
                    conn.send(("cmd", seq, False,
                               f"{type(e).__name__}: {e}"))
                continue
            if tag != "submit":
                continue
            # 5-element frame carries the router-side trace id; the
            # 4-element form is accepted for wire compat (a parent one
            # commit ahead of a child, or vice versa)
            _, token, key, b = msg[:4]
            tid = msg[4] if len(msg) > 4 else ""
            if chaos is not None:
                stall = chaos.replica_stall_s(rid)
                if stall > 0:
                    time.sleep(stall)       # slow, NOT dead
                if chaos.replica_quarantined(rid):
                    conn.send(("quarantined", token,
                               "chaos quarantine_replica"))
                    continue
                if chaos.replica_kill_due(rid, batches):
                    os.kill(os.getpid(), signal.SIGKILL)
            try:
                srv = cache.get(key)
                t = srv.submit(b, parent=parent_ref(tid))
                srv.flush()
                x = np.asarray(t.result(300.0))
                batches += 1
                conn.send(("ok", token, x))
            except (FactorCorruptError, CheckpointError) as e:
                # handle-level failure: quarantine the replica, leave
                # the token undelivered — the parent re-routes it
                conn.send(("quarantined", token,
                           f"{type(e).__name__}: {e}"))
            except Exception as e:          # noqa: BLE001 — per-ticket
                conn.send(("err", token, type(e).__name__, str(e)))
    finally:
        try:
            # final metrics push: the parent absorbs whatever this
            # replica counted, even across a graceful close (a kill -9
            # forfeits it — the delta-merge makes that loss bounded)
            from superlu_dist_tpu.obs.metrics import get_metrics
            m = get_metrics()
            if m.enabled:
                conn.send(("metrics", m.snapshot()))
        except Exception:                   # noqa: BLE001 — teardown
            pass
        try:
            cache.close()
        except Exception:                   # noqa: BLE001 — teardown
            pass


class ProcessReplica:
    """Subprocess replica behind the same interface: a spawned worker
    process (fork would inherit jax/XLA locks) serving over a duplex
    pipe, judged alive by the PR 8 detector verdict
    (:func:`~superlu_dist_tpu.parallel.treecomm.pid_alive`) — the
    kill -9 failure domain the ``fleet-failover`` CI gate exercises."""

    kind = "process"

    def __init__(self, rid: int, router: "FleetRouter", paths: dict,
                 server_kw: dict, handle_bytes: int | None):
        self.rid = int(rid)
        self._router = router
        ctx = multiprocessing.get_context("spawn")
        self._conn, child = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(
            target=_replica_child_main,
            args=(child, rid, dict(paths), dict(server_kw),
                  handle_bytes),
            name=f"slu-fleet-replica-{rid}", daemon=True)
        self._proc.start()
        child.close()
        self._lock = make_lock(f"ProcessReplica[{rid}]._lock")
        self._send_lock = make_lock(f"ProcessReplica[{rid}]._send_lock")
        self._closed = False
        self._dead = False
        self._quarantined = False
        self._keys_routed: set = set()      # parent-side affinity guess
        self._cmd_seq = 0
        self._cmd_boxes: dict = {}          # seq -> (event, box)
        self._collector = threading.Thread(
            target=self._collect, name=f"slu-fleet-collect-{rid}",
            daemon=True)
        self._collector.start()

    @property
    def pid(self) -> int:
        return int(self._proc.pid or -1)

    # -- interface ------------------------------------------------------
    def _send(self, msg) -> bool:
        with self._send_lock:
            try:
                self._conn.send(msg)
            except (OSError, ValueError, BrokenPipeError):
                return False
        return True

    def submit(self, rec: _TicketRec) -> bool:
        with self._lock:
            if self._closed or self._dead or self._quarantined:
                return False
            self._keys_routed.add(rec.key)
        return self._send(("submit", rec.token, rec.key, rec.b,
                           rec.ctx.trace_id))

    def register(self, key, path: str) -> None:
        self._send(("register", key, path))

    def _run_cmd(self, msg_head: tuple, timeout: float = 120.0):
        done = threading.Event()
        box = {}
        with self._lock:
            if self._closed or self._dead:
                raise SuperLUError(
                    f"fleet replica {self.rid} is not accepting "
                    "commands (closed or failed)")
            self._cmd_seq += 1
            seq = self._cmd_seq
            self._cmd_boxes[seq] = (done, box)
        if not self._send((msg_head[0], seq) + msg_head[1:]):
            raise SuperLUError(
                f"fleet replica {self.rid} pipe is down")
        if not done.wait(timeout):
            raise SuperLUError(
                f"fleet replica {self.rid} command timed out "
                f"({timeout}s)")
        if not box.get("ok"):
            raise SuperLUError(str(box.get("val")))
        return box.get("val")

    def deploy(self, key, path: str) -> bool:
        return bool(self._run_cmd(("deploy", key, path)))

    def poll_metrics(self, timeout: float = 5.0):
        """Pull the child's metrics snapshot over the command channel
        and fold the delta into the router registry (the process-
        replica aggregation satellite).  Returns the raw snapshot."""
        snap = self._run_cmd(("metrics_pull",), timeout=timeout)
        if snap:
            self._router._absorb_replica_metrics(self.rid, snap)
        return snap

    def canary(self, key, b: np.ndarray) -> np.ndarray:
        return np.asarray(self._run_cmd(("canary", key, b)))

    def alive(self) -> bool:
        """The PR 8 verdict on the replica process itself: pid exists
        and is not a zombie.  A stalled-but-alive replica is NEVER
        declared failed."""
        with self._lock:
            if self._dead:
                return False
            if self._closed:
                return True
        return pid_alive(self.pid)

    def routable(self) -> bool:
        with self._lock:
            if self._closed or self._dead or self._quarantined:
                return False
        return pid_alive(self.pid)

    def affinity(self, key) -> bool:
        with self._lock:
            return key in self._keys_routed

    def describe(self) -> dict:
        with self._lock:
            return {"rid": self.rid, "kind": self.kind, "pid": self.pid,
                    "dead": self._dead,
                    "quarantined": self._quarantined,
                    "keys_routed": sorted(map(repr, self._keys_routed))}

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
        self._send(("close",))
        self._proc.join(timeout)
        if self._proc.is_alive():
            self._proc.kill()
            self._proc.join(1.0)
        try:
            self._conn.close()
        except OSError:
            pass
        self._collector.join(1.0)

    # -- collector ------------------------------------------------------
    def _collect(self):
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError, ValueError):
                with self._lock:
                    was_closed = self._closed or self._dead
                    self._dead = True
                self._fail_cmds_locked_free()
                if not was_closed:
                    self._router._replica_failed(
                        self.rid,
                        cause=f"pipe to replica pid {self.pid} closed "
                              "(process dead)",
                        pid=self.pid)
                return
            tag = msg[0]
            if tag == "ok":
                self._router._deliver_token(msg[1], x=msg[2],
                                            rid=self.rid)
            elif tag == "err":
                self._router._deliver_token(
                    msg[1],
                    err=_RemoteServeError(msg[2], msg[3], self.rid),
                    rid=self.rid)
            elif tag == "quarantined":
                with self._lock:
                    already = self._quarantined
                    self._quarantined = True
                if not already:
                    self._router._replica_unroutable(self.rid, msg[2])
            elif tag == "metrics":
                self._router._absorb_replica_metrics(self.rid, msg[1])
            elif tag == "cmd":
                _, seq, ok, val = msg
                with self._lock:
                    ent = self._cmd_boxes.pop(seq, None)
                if ent is not None:
                    done, box = ent
                    box["ok"] = ok
                    box["val"] = val
                    done.set()

    def _fail_cmds_locked_free(self):
        """Resolve every pending command box after the pipe died (no
        command may hang on a dead replica)."""
        with self._lock:
            boxes = list(self._cmd_boxes.values())
            self._cmd_boxes.clear()
        for done, box in boxes:
            box["ok"] = False
            box["val"] = f"replica {self.rid} died mid-command"
            done.set()


# ---------------------------------------------------------------------------
# the routing front
# ---------------------------------------------------------------------------

class FleetRouter:
    """Health-checked routing front over N multi-handle replicas.

    Parameters
    ----------
    bundles : dict
        ``{key: persist bundle dir}`` registered on every replica at
        construction (more via :meth:`register`).
    n_replicas / kind :
        Fleet shape; None reads ``SLU_TPU_FLEET_REPLICAS`` /
        ``SLU_TPU_FLEET_KIND`` (``thread`` or ``process``).
    queue_max :
        Fleet-level admission cap in undelivered COLUMNS; None reads
        ``SLU_TPU_FLEET_QUEUE_MAX`` (0 = unbounded).
    deadline_s :
        End-to-end per-ticket deadline; None reads
        ``SLU_TPU_FLEET_DEADLINE_MS`` (0 = off).
    handle_bytes :
        Per-replica resident-handle byte budget; None reads
        ``SLU_TPU_FLEET_HANDLE_BYTES``.
    health_s :
        Health-monitor poll period; None reads
        ``SLU_TPU_FLEET_HEALTH_S``.
    server_kw :
        SolveServer keywords for replica-loaded handles (defaults to
        :data:`FLEET_SERVER_KW` — the determinism contract).
    """

    def __init__(self, bundles: dict | None = None,
                 n_replicas: int | None = None, kind: str | None = None,
                 queue_max: int | None = None,
                 deadline_s: float | None = None,
                 handle_bytes: int | None = None,
                 health_s: float | None = None,
                 server_kw: dict | None = None):
        from superlu_dist_tpu.utils.options import (env_float, env_int,
                                                    env_str)
        if n_replicas is None:
            n_replicas = env_int("SLU_TPU_FLEET_REPLICAS")
        if kind is None:
            kind = env_str("SLU_TPU_FLEET_KIND")
        if kind not in ("thread", "process"):
            raise SuperLUError(
                f"fleet replica kind must be 'thread' or 'process', "
                f"got {kind!r}")
        if queue_max is None:
            queue_max = env_int("SLU_TPU_FLEET_QUEUE_MAX")
        if deadline_s is None:
            deadline_s = env_float("SLU_TPU_FLEET_DEADLINE_MS") / 1000.0
        if health_s is None:
            health_s = env_float("SLU_TPU_FLEET_HEALTH_S")
        self.n_replicas = int(n_replicas)
        if self.n_replicas < 1:
            raise SuperLUError("a fleet needs at least one replica")
        self.kind = kind
        self.queue_max = int(queue_max)
        self.deadline_s = float(deadline_s)
        self.health_s = float(health_s)
        self._server_kw = dict(FLEET_SERVER_KW if server_kw is None
                               else server_kw)
        self._handle_bytes = handle_bytes
        self._lock = make_lock("FleetRouter._lock")
        self._cond = make_condition("FleetRouter._cond", self._lock)
        self._registry: dict = {}
        self._undelivered: dict = {}        # token -> _TicketRec
        self._pending_cols = 0
        self._seq = 0
        self._rr = 0                        # round-robin tiebreak
        self._closed = False
        self._draining = False
        self._failed: set = set()
        self._unroutable_seen: set = set()
        # counters (under _lock; metrics registry mirrors when on)
        self._requests = 0
        self._delivered = 0
        self._errors = 0
        self._shed = 0
        self._deadline_miss = 0
        self._reroutes = 0
        self._failovers = 0
        self._deploys = 0
        self._refactors = 0
        self._rollbacks = 0
        m = get_metrics()
        self._metrics = m if m.enabled else None
        # latched once (the NULL_TRACER discipline): submit mints a
        # TicketContext only when tracing is on
        t = get_tracer()
        self._tracer = t if t.enabled else None
        self._accounter = get_accounter()    # always-on latency floor
        self._slo = SLOEvaluator()
        self._slo_state: dict = {}
        self._replica_snaps: dict = {}      # rid -> last absorbed snap
        bundles = dict(bundles or {})
        self._registry.update(
            {k: str(p) for k, p in bundles.items()})
        cls = ThreadReplica if kind == "thread" else ProcessReplica
        self._replicas = [
            cls(rid, self, self._registry, self._server_kw,
                handle_bytes)
            for rid in range(self.n_replicas)]
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="slu-fleet-monitor",
            daemon=True)
        self._monitor.start()
        self._gauge_healthy()

    # ------------------------------------------------------------------
    def register(self, key, bundle_path: str) -> dict:
        """Bind ``key`` to a persist bundle fleet-wide.  Returns the
        bundle's lu_meta peek."""
        from superlu_dist_tpu.persist.serial import lu_meta
        meta = lu_meta(str(bundle_path))
        with self._lock:
            self._registry[key] = str(bundle_path)
        for r in self._replicas:
            r.register(key, str(bundle_path))
        return meta

    def keys(self) -> list:
        with self._lock:
            return list(self._registry)

    # ------------------------------------------------------------------
    def submit(self, key, b: np.ndarray) -> FleetTicket:
        """Route one right-hand side for matrix ``key`` — (n,) or
        (n, k) — to a healthy replica.  Admission control runs HERE:
        closed fleet → :class:`ServerClosedError`; draining or past the
        fleet column cap → :class:`ServeOverloadError` (reason
        ``draining`` / ``fleet_queue_full``) before any replica sees
        the work."""
        t0 = time.perf_counter()
        b = np.asarray(b)
        squeeze = b.ndim == 1
        b2 = b[:, None] if squeeze else b
        if b2.ndim != 2 or b2.shape[1] == 0:
            raise SuperLUError(
                f"rhs shape {b.shape} does not fit a fleet submit "
                "(need (n,) or (n, k>0))")
        k = b2.shape[1]
        m = self._metrics
        with self._lock:
            if self._closed:
                raise ServerClosedError("FleetRouter is closed")
            if key not in self._registry:
                raise SuperLUError(
                    f"matrix key {key!r} is not registered with this "
                    "fleet (register(key, bundle_path) first)")
            if self._draining:
                self._shed += 1
                if m is not None:
                    m.inc("slu_serve_shed_total", 1.0,
                          reason="draining")
                raise ServeOverloadError(k, self._pending_cols,
                                         self.queue_max,
                                         reason="draining")
            if self.queue_max > 0 and \
                    self._pending_cols + k > self.queue_max:
                self._shed += 1
                if m is not None:
                    m.inc("slu_serve_shed_total", 1.0,
                          reason="fleet_queue_full")
                raise ServeOverloadError(k, self._pending_cols,
                                         self.queue_max,
                                         reason="fleet_queue_full")
            self._seq += 1
            rec = _TicketRec(self._seq, key, b2, squeeze)
            rec.t_submit = t0
            rec.t_routed = t0
            if self._tracer is not None:
                rec.ctx = TicketContext(f"f{rec.token}", t0)
                rec.ctx.note(nrhs=k, key=str(key))
            if self.deadline_s > 0:
                rec.deadline_s = self.deadline_s
                rec.t_deadline = t0 + self.deadline_s
            self._undelivered[rec.token] = rec
            self._pending_cols += k
            self._requests += 1
        if m is not None:
            m.inc("slu_fleet_requests_total", 1.0)
            m.inc("slu_fleet_columns_total", float(k))
        self._route(rec)
        return FleetTicket(rec, self)

    def solve(self, key, b: np.ndarray,
              timeout: float | None = None) -> np.ndarray:
        """submit() + result(): the one-call convenience path."""
        return self.submit(key, b).result(timeout)

    # ------------------------------------------------------------------
    def _pick_locked(self, key, exclude):
        """Under the lock: choose a routable replica — handle affinity
        first, least outstanding columns second, round-robin third.
        Returns the replica index or None when no routable replica
        remains."""
        cands = [i for i, r in enumerate(self._replicas)
                 if i not in exclude and r.routable()]
        if not cands:
            return None
        out = {i: 0 for i in cands}
        for rec in self._undelivered.values():
            if rec.replica in out and not rec.event.is_set():
                out[rec.replica] += rec.k
        with_key = [i for i in cands if self._replicas[i].affinity(key)]
        pool = with_key or cands
        best = min(out[i] for i in pool)
        pool = [i for i in pool if out[i] == best]
        self._rr += 1
        return pool[self._rr % len(pool)]

    def _route(self, rec: _TicketRec, rerouted: bool = False) -> None:
        """Assign ``rec`` to a replica (retrying refusals against the
        remaining healthy set).  When NO routable replica remains the
        ticket is delivered a structured :class:`ReplicaFailureError`
        instead of hanging — the only time a fleet client sees a
        replica failure."""
        m = self._metrics
        while True:
            with self._lock:
                if rec.event.is_set() or \
                        rec.token not in self._undelivered:
                    return
                over_budget = rec.attempts > 2 * self.n_replicas + 2
                rid = (None if over_budget else
                       self._pick_locked(rec.key, exclude=rec.tried))
                if rid is None and rec.tried and not over_budget:
                    # every replica tried once: allow a second lap over
                    # whatever is still routable (a replica may have
                    # refused transiently)
                    rid = self._pick_locked(rec.key, exclude=())
                if rid is not None:
                    rec.replica = rid
                    rec.tried.add(rid)
                    rec.attempts += 1
            if rid is None:
                err = ReplicaFailureError(
                    rec.replica, [rec.token],
                    cause="no healthy replica remains to re-route to",
                    kind=self.kind)
                self._deliver(rec, err=err, rid=rec.replica)
                return
            if self._replicas[rid].submit(rec):
                if rec.ctx.enabled:
                    # stage edge: routing time since submit (or since
                    # the previous route on a failover lap)
                    tnow = time.perf_counter()
                    rec.ctx.stage("reroute" if rerouted else "route",
                                  rec.t_routed, tnow - rec.t_routed)
                    rec.ctx.note(replica=rid)
                    rec.t_routed = tnow
                if rerouted:
                    with self._lock:
                        self._reroutes += 1
                    if m is not None:
                        m.inc("slu_fleet_reroutes_total", 1.0)
                return
            rerouted = True     # refusal → the next lap is a re-route

    # ------------------------------------------------------------------
    def _deliver(self, rec: _TicketRec, x=None, err=None,
                 rid: int = -1) -> bool:
        """First-wins delivery under the idempotent retry token: a
        duplicate delivery (original replica raced its own failover) is
        dropped, which is what makes re-routing safe."""
        with self._lock:
            if rec.event.is_set() or \
                    self._undelivered.pop(rec.token, None) is None:
                return False
            self._pending_cols -= rec.k
            if err is not None:
                rec.error = err
                self._errors += 1
            else:
                rec.x = x
                self._delivered += 1
            rec.event.set()
            self._cond.notify_all()
        t_end = time.perf_counter()
        lat = t_end - rec.t_submit
        # the always-on latency floor: one histogram increment per
        # delivered (or errored) ticket, keyed by traffic class
        self._accounter.observe(rec.k, lat, klass="fleet")
        ctx = rec.ctx
        if ctx.enabled:
            ctx.stage("serve", rec.t_routed, t_end - rec.t_routed)
            if err is not None:
                ctx.note(error=type(err).__name__)
            ctx.emit(self._tracer, t_end, name="fleet-request")
        m = self._metrics
        if m is not None:
            m.observe("slu_fleet_route_seconds", lat)
        return True

    def _deliver_token(self, token: int, x=None, err=None,
                       rid: int = -1) -> bool:
        with self._lock:
            rec = self._undelivered.get(token)
        if rec is None:
            return False
        return self._deliver(rec, x=x, err=err, rid=rid)

    def _expire(self, rec: _TicketRec, now: float) -> bool:
        """End-to-end deadline expiry (monitor sweep or the waiting
        ticket itself)."""
        if rec.t_deadline is None or now < rec.t_deadline:
            return False
        err = ServeDeadlineError(rec.deadline_s, now - rec.t_submit,
                                 rec.k)
        if self._deliver(rec, err=err, rid=rec.replica):
            with self._lock:
                self._deadline_miss += 1
            if self._metrics is not None:
                self._metrics.inc("slu_serve_deadline_miss_total", 1.0)
            # _deliver recorded the final serve stage; attach the
            # timings so the postmortem names the stage that ate the
            # budget, then dump — outside every lock (SLU109)
            if rec.ctx.enabled:
                err.ticket_stages = rec.ctx.stages_ms() or None
                err.trace_id = rec.ctx.trace_id
            err.flight_postmortem()
            return True
        return False

    # ------------------------------------------------------------------
    def _replica_failed(self, rid: int, cause: str,
                        pid: int = -1) -> None:
        """A replica is DEAD (pid gone / pipe closed / worker crashed):
        re-route every ticket it had accepted but not delivered.  The
        :class:`ReplicaFailureError` constructed here dumps the
        flight-recorder postmortem naming the dead replica and the
        re-routed ticket set — the tickets themselves never see it
        while a healthy replica remains."""
        with self._lock:
            if self._closed or rid in self._failed:
                return
            self._failed.add(rid)
            victims = [rec for rec in self._undelivered.values()
                       if rec.replica == rid and not rec.event.is_set()]
            self._failovers += 1
        # construct (and flight-dump) OUTSIDE the lock: the postmortem
        # write must not stall submit/deliver (SLU109 hold discipline)
        ReplicaFailureError(rid, [rec.token for rec in victims],
                            cause=cause, pid=pid, kind=self.kind)
        m = self._metrics
        if m is not None:
            m.inc("slu_fleet_failovers_total", 1.0)
        self._gauge_healthy()
        for rec in victims:
            self._route(rec, rerouted=True)

    def _replica_unroutable(self, rid: int, cause: str) -> None:
        """A replica QUARANTINED (corrupt handle, chaos): alive but
        unroutable — re-route its undelivered tickets, route around it
        from now on.  Same evidence trail as a death, kind
        ``quarantine``."""
        with self._lock:
            if self._closed or rid in self._unroutable_seen:
                return
            self._unroutable_seen.add(rid)
            victims = [rec for rec in self._undelivered.values()
                       if rec.replica == rid and not rec.event.is_set()]
            self._failovers += 1
        ReplicaFailureError(rid, [rec.token for rec in victims],
                            cause=cause, kind="quarantine")
        m = self._metrics
        if m is not None:
            m.inc("slu_fleet_failovers_total", 1.0)
        self._gauge_healthy()
        for rec in victims:
            self._route(rec, rerouted=True)

    def _gauge_healthy(self) -> None:
        if self._metrics is not None:
            n = sum(1 for r in self._replicas if r.routable())
            self._metrics.set("slu_fleet_replicas_healthy", float(n))

    def _monitor_loop(self):
        """Health monitor: replica liveness probes (the pid/thread
        verdict — NEVER latency, so a slow replica yields zero false
        failovers), deadline sweeps, and the healthy-replicas gauge."""
        while not self._monitor_stop.wait(self.health_s):
            for rid, r in enumerate(self._replicas):
                with self._lock:
                    seen = rid in self._failed or self._closed
                if not seen and not r.alive():
                    self._replica_failed(
                        rid, cause="liveness probe: replica "
                        f"{r.kind} is dead",
                        pid=getattr(r, "pid", -1))
            if self.deadline_s > 0:
                now = time.perf_counter()
                with self._lock:
                    due = [rec for rec in self._undelivered.values()
                           if rec.t_deadline is not None
                           and now >= rec.t_deadline]
                for rec in due:
                    self._expire(rec, now)
            self._gauge_healthy()
            self._heartbeat_obs()

    def _heartbeat_obs(self) -> None:
        """Observability heartbeat (piggybacks the health poll): pull
        process-replica child metrics into the router registry, publish
        the latency quantile gauges, evaluate the SLO burn rate, and
        refresh the metrics export snapshot — so ``slu_top`` reading
        the export file sees a live fleet, not an atexit one."""
        m = self._metrics
        if m is not None and self.kind == "process":
            for r in self._replicas:
                try:
                    r.poll_metrics()
                except Exception:           # noqa: BLE001 — best effort
                    pass
        if m is not None:
            self._accounter.publish(m)
        if self._slo.armed:
            state = self._slo.evaluate(self._accounter)
            with self._lock:
                self._slo_state = state
            if m is not None:
                for key, s in state.items():
                    klass, _, nb = key.partition("|")
                    labels = {"class": klass, "nrhs": nb}
                    m.set("slu_slo_burn_rate", float(s["burn"]),
                          **labels)
                    m.set("slu_slo_ok", 1.0 if s["ok"] else 0.0,
                          **labels)
        if m is not None:
            m.dump_now()

    def _absorb_replica_metrics(self, rid: int, snap: dict) -> None:
        """Fold a process-replica child's metrics snapshot into the
        router registry as a DELTA vs the last snapshot absorbed from
        that replica — heartbeat pulls and the teardown push both land
        here, so double counting is structurally impossible."""
        m = self._metrics
        if m is None or not snap:
            return
        with self._lock:
            base = self._replica_snaps.get(rid)
            self._replica_snaps[rid] = snap
        m.merge_snapshot(snap, base=base)

    # ------------------------------------------------------------------
    def deploy(self, bundle_path: str, key=None,
               canary_b: np.ndarray | None = None, a=None,
               berr_max: float = 0.0, preflight: bool = True) -> dict:
        """Rolling deploy of a new bundle for ``key`` (defaults to the
        fleet's only key): one replica at a time, swap behind the
        per-replica drain point (the in-band command — queued + future
        tickets served by the new handle, in-flight finishes on the
        old, zero dropped), then gate on a canary batch served through
        THAT replica: finite X always, componentwise BERR ≤
        ``berr_max`` when ``a`` (the new matrix) and a positive gate
        are given.  Any load/scrub/canary failure rolls every
        already-swapped replica back to the previous bundle and raises
        :class:`DeployRollbackError` — the fleet never serves a mix of
        good and poisoned factors.  With ``preflight`` (default) the
        bundle is side-loaded and canaried in the ROUTER first, so a
        poisoned bundle is rejected before any replica ever swaps to it
        (zero exposure); the per-replica canary still guards
        replica-local failures during the roll.  Returns a summary
        dict."""
        from superlu_dist_tpu.persist.serial import lu_meta
        with self._lock:
            if self._closed:
                raise ServerClosedError("FleetRouter is closed")
            if key is None:
                if len(self._registry) != 1:
                    raise SuperLUError(
                        "deploy(bundle) needs key=... when the fleet "
                        f"serves {len(self._registry)} keys")
                key = next(iter(self._registry))
            if key not in self._registry:
                raise SuperLUError(
                    f"matrix key {key!r} is not registered with this "
                    "fleet")
            old_path = self._registry[key]
        bundle_path = str(bundle_path)
        try:
            meta = lu_meta(bundle_path)     # manifest sanity, pre-flight
        except Exception as e:
            self._note_rollback()
            raise DeployRollbackError(key, bundle_path, "load",
                                      cause=f"{type(e).__name__}: {e}")
        if canary_b is None:
            # deterministic default canary: a ones RHS of the bundle's
            # n in the bundle's factor dtype
            try:
                dt = np.dtype(meta.get("factor_dtype", "float64"))
            except TypeError:
                dt = np.float64
            canary_b = np.ones(int(meta["n"]), dtype=dt)

        def _gate(x, where: str) -> None:
            if not np.isfinite(x).all():
                raise SuperLUError(
                    f"{where} canary batch produced non-finite X")
            if a is not None and berr_max > 0:
                from superlu_dist_tpu.refine.ir import request_berrs
                berr = float(request_berrs(a, canary_b, x).max())
                if berr > berr_max:
                    raise SuperLUError(
                        f"{where} canary berr {berr:.3e} exceeds the "
                        f"{berr_max:.3e} gate")

        if preflight:
            # side-load + canary in the ROUTER before any replica swaps:
            # a poisoned bundle never reaches a serving handle
            from superlu_dist_tpu.persist.serial import load_lu
            try:
                lu_new = load_lu(bundle_path)   # digest-verified (scrub)
            except Exception as e:              # noqa: BLE001 — gate
                self._note_rollback()
                raise DeployRollbackError(
                    key, bundle_path, "load",
                    cause=f"{type(e).__name__}: {e}")
            try:
                _gate(np.asarray(lu_new.solve_factored(canary_b)),
                      "preflight")
            except Exception as e:              # noqa: BLE001 — gate
                self._note_rollback()
                raise DeployRollbackError(
                    key, bundle_path, "canary",
                    cause=f"{type(e).__name__}: {e}")
            finally:
                del lu_new
        swapped: list = []
        for rid, r in enumerate(self._replicas):
            if not r.routable():
                continue
            try:
                r.deploy(key, bundle_path)
                swapped.append(rid)
                _gate(r.canary(key, canary_b), f"replica {rid}")
            except Exception as e:          # noqa: BLE001 — roll back
                restored = []
                for back in swapped:
                    try:
                        self._replicas[back].deploy(key, old_path)
                        restored.append(back)
                    except Exception:       # noqa: BLE001 — best effort
                        pass
                self._note_rollback()
                # deploy() failing = the swap's digest-verified load /
                # scrub rejected the bundle; past it, the canary did
                stage = "canary" if rid in swapped else "load"
                raise DeployRollbackError(
                    key, bundle_path, stage, replica=rid,
                    rolled_back=restored,
                    cause=f"{type(e).__name__}: {e}")
        with self._lock:
            self._registry[key] = bundle_path
            self._deploys += 1
        for r in self._replicas:
            r.register(key, bundle_path)
        if self._metrics is not None:
            self._metrics.inc("slu_fleet_deploys_total", 1.0)
        return {"key": key, "bundle": bundle_path,
                "replicas_swapped": swapped, "previous": old_path}

    def _note_rollback(self):
        with self._lock:
            self._rollbacks += 1
        if self._metrics is not None:
            self._metrics.inc("slu_fleet_rollbacks_total", 1.0)

    # ------------------------------------------------------------------
    def refactor(self, key, new_values, canary_b: np.ndarray | None = None,
                 berr_max: float = 0.0, workdir: str | None = None,
                 preflight: bool = True) -> dict:
        """Rolling same-pattern refactorization of the fleet's handle
        for ``key``: the registered bundle is loaded router-side, its
        numeric phase re-run over ``new_values`` (same-pattern
        SparseCSR) through the crash-consistent
        ``drivers.gssvx.refactor`` pipeline — symbolic, plan, and
        compiled programs reused, BERR-canaried, adopted only on
        success — persisted as a sibling bundle, and rolled across the
        replicas one at a time through the :meth:`deploy` drain-point +
        canary machinery (zero dropped tickets; values cross the pipe
        as a bundle, the replica protocol is unchanged).  Failure at
        ANY stage raises
        :class:`~superlu_dist_tpu.utils.errors.RefactorRollbackError`
        with every already-swapped replica restored to the previous
        bundle, which keeps serving — the fleet never mixes old and new
        factors.  Pattern drift raises ``PatternMismatchError`` before
        anything is touched.  Returns the :meth:`deploy` summary dict
        plus the new bundle path."""
        from superlu_dist_tpu.drivers.gssvx import refactor as _refactor
        from superlu_dist_tpu.persist.serial import load_lu, save_lu
        with self._lock:
            if self._closed:
                raise ServerClosedError("FleetRouter is closed")
            if key not in self._registry:
                raise SuperLUError(
                    f"matrix key {key!r} is not registered with this "
                    "fleet")
            old_path = self._registry[key]
            seq = self._refactors + self._rollbacks
        if self._metrics is not None:
            self._metrics.inc("slu_fleet_refactor_total", 1.0)
        try:
            lu = load_lu(old_path)
        except Exception as e:              # noqa: BLE001 — gate
            self._note_rollback()
            raise RefactorRollbackError(
                key, "load", cause=f"{type(e).__name__}: {e}")
        try:
            _refactor(lu, new_values, canary_b=canary_b,
                      berr_max=berr_max)
        except RefactorRollbackError as e:
            # the shadow factorization/canary already rolled back at
            # the handle level; nothing was persisted, no replica saw it
            self._note_rollback()
            raise RefactorRollbackError(
                key, e.stage, cause=e.cause or "handle-level refactor "
                "rolled back", berr=e.berr,
                berr_target=e.berr_target) from e
        new_path = (os.path.join(workdir, f"refactor-{seq:04d}")
                    if workdir is not None
                    else f"{old_path}.refactor-{seq:04d}")
        try:
            save_lu(lu, new_path)
        except Exception as e:              # noqa: BLE001 — gate
            self._note_rollback()
            raise RefactorRollbackError(
                key, "persist", cause=f"{type(e).__name__}: {e}")
        a_gate = lu.a if berr_max > 0 else None
        try:
            summary = self.deploy(new_path, key=key, canary_b=canary_b,
                                  a=a_gate, berr_max=berr_max,
                                  preflight=preflight)
        except DeployRollbackError as e:
            # deploy() already restored every swapped replica and noted
            # the rollback; surface it under the refactor contract
            raise RefactorRollbackError(
                key, e.stage, replica=e.replica,
                rolled_back=e.rolled_back, cause=e.cause) from e
        with self._lock:
            self._refactors += 1
        if self._metrics is not None:
            self._metrics.inc("slu_fleet_refactor_adopted_total", 1.0)
        summary["previous"] = old_path
        summary["bundle"] = new_path
        return summary

    # ------------------------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Reject new submits (``ServeOverloadError`` reason
        ``draining``) while finishing everything undelivered.  True
        once empty."""
        end = None if timeout is None else time.perf_counter() + timeout
        with self._lock:
            self._draining = True
            while self._undelivered:
                left = None if end is None else end - time.perf_counter()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(min(left, 0.5) if left is not None
                                else 0.5)
            return True

    def resume(self) -> "FleetRouter":
        with self._lock:
            self._draining = False
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Shut the fleet down: stop the monitor, close every replica,
        then deliver :class:`ServerClosedError` to every still-
        undelivered ticket — a fleet waiter can never hang on a fleet
        that no longer exists (the server-tier close contract, lifted)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._monitor_stop.set()
        self._monitor.join(min(timeout, 5.0))
        for r in self._replicas:
            r.close(timeout=timeout / max(len(self._replicas), 1))
        with self._lock:
            recs = list(self._undelivered.values())
            self._undelivered.clear()
            self._pending_cols = 0
        for rec in recs:
            if not rec.event.is_set():
                rec.error = ServerClosedError(
                    "FleetRouter closed before this ticket was "
                    "delivered")
                rec.event.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            st = {
                "replicas": self.n_replicas,
                "kind": self.kind,
                "replicas_failed": sorted(self._failed),
                "requests": self._requests,
                "delivered": self._delivered,
                "errors": self._errors,
                "shed": self._shed,
                "deadline_miss": self._deadline_miss,
                "reroutes": self._reroutes,
                "failovers": self._failovers,
                "deploys": self._deploys,
                "refactors": self._refactors,
                "rollbacks": self._rollbacks,
                "pending_cols": self._pending_cols,
                "queue_max": self.queue_max,
                "deadline_s": self.deadline_s,
                "keys": len(self._registry),
                "closed": self._closed,
                "draining": self._draining,
                "slo": dict(self._slo_state),
            }
        st["replicas_healthy"] = sum(
            1 for r in self._replicas if r.routable())
        st["replica_detail"] = [r.describe() for r in self._replicas]
        return st
