"""SLU117 clean-negative fixture: df64 pairs flow only through the
ops/df64 primitives (merge via df64_to_f64, arithmetic via df64_*), and
a local two_sum fences every compensation op behind the barrier alias —
the shape ops/df64.py itself uses."""
from superlu_dist_tpu.ops.df64 import df64_add, df64_mul, df64_to_f64


def combine(xh, xl, yh, yl):
    sh, sl = df64_add(xh, xl, yh, yl)
    ph, pl = df64_mul(sh, sl, yh, yl)
    return df64_to_f64(ph, pl)             # sanctioned merge


def two_sum(a, b):
    from jax.lax import optimization_barrier as _bar
    s = _bar(a + b)
    bb = _bar(s - a)
    return s, _bar((a - bb) + (b - bb))
