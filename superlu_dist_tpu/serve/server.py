"""Micro-batching solve server — the serving tier over a factored handle.

``SolveServer`` owns one factored :class:`LUFactorization` (taken live
from a ``gssvx`` result, or loaded zero-refactor from a ``persist/``
bundle via :meth:`SolveServer.from_bundle` — FACT time stays 0.0) and
turns "one matrix, one solve" into a request/response loop:

* callers :meth:`submit` right-hand-side columns (original labeling,
  ``A·x = b``) and get a :class:`SolveTicket` back immediately;
* a dispatcher thread coalesces pending columns into micro-batches
  **keyed to the device solver's compiled nrhs buckets** (solve/plan.py)
  — the oldest pending request is held open for at most
  ``SLU_TPU_SERVE_MAX_WAIT_MS`` so concurrent traffic lands in one
  device dispatch instead of many, and a batch dispatches early the
  moment it can fill ``SLU_TPU_SERVE_MAX_BATCH`` columns (default: the
  nrhs bucket cap);
* each batch is ONE solve through the handle (device sweeps on an
  accelerator backend, the host supernodal solve otherwise — the same
  auto/fallback discipline as the driver), whose results are scattered
  back to the submitting tickets.

Requests wider than the batch cap are column-split across consecutive
batches transparently — a ticket completes when all its columns have.

Reliability layer (docs/SERVING.md failure-domain matrix):

* **Admission control + load shedding** — ``SLU_TPU_SERVE_QUEUE_MAX``
  bounds the pending-column queue (excess submits shed with
  :class:`ServeOverloadError` instead of queueing forever) and
  ``SLU_TPU_SERVE_DEADLINE_MS`` arms a per-request deadline (columns
  still queued past it expire with :class:`ServeDeadlineError` —
  checked by the dispatcher AND by the waiting ticket itself, so a
  stalled dispatcher cannot hang an expired waiter).  :meth:`drain`
  finishes in-flight work while rejecting new submissions.
* **Poisoned-request isolation** — a batch whose solve produces
  non-finite columns (or raises ``NumericBreakdownError``) is bisected
  to the offending columns; the healthy columns are re-served at the
  ORIGINAL batch width, which keeps them bit-identical to an unpoisoned
  dispatch (per-column independence of the batched sweeps), and only
  the offending tickets fail, with :class:`ServePoisonedError` naming
  their columns.  ``SLU_TPU_SERVE_BERR_MAX`` additionally gates
  per-request residual quality: a completing ticket whose componentwise
  berr exceeds the gate is routed through a per-ticket iterative-
  refinement rung (``refine/ir.refine_ticket``) before delivery.
* **Hot handle swap + factor scrubbing** — :meth:`swap` atomically
  replaces the factored handle between batches (queued tickets are
  served by the new handle; nothing is dropped — the refactor-on-
  degrade path), and ``SLU_TPU_SERVE_SCRUB_S`` arms a background
  scrubber that re-hashes the handle's resident panel stacks against
  their persist-bundle sha256 digests, quarantining the handle with
  :class:`FactorCorruptError` on mismatch instead of silently serving
  garbage X.

Observability: every batch runs under a ``serve-batch`` dispatch span
(the device solve's own ``device-solve`` kernel span and ``solve-d2h``
comm span nest inside it), and the metrics registry (obs/metrics.py,
``SLU_TPU_METRICS``) accumulates the serving-grade series —
``slu_serve_requests_total`` / ``_columns_total`` / ``_batches_total``
/ ``_errors_total`` / ``_shed_total`` / ``_deadline_miss_total`` /
``_poisoned_total`` / ``_refined_total`` / ``_swaps_total`` /
``_scrub_{runs,failures}_total`` counters, the ``slu_serve_queue_depth``
gauge, and ``slu_serve_request_seconds`` / ``slu_serve_batch_fill`` /
``slu_serve_queue_wait_seconds`` histograms.  docs/SERVING.md walks the
whole tier.
"""

from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.slo import NULL_TICKET, TicketContext, get_accounter
from superlu_dist_tpu.utils.lockwatch import make_condition, make_lock
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.solve.plan import bucket_nrhs
from superlu_dist_tpu.utils.errors import (
    FactorCorruptError, NumericBreakdownError, ServeDeadlineError,
    ServeOverloadError, ServePoisonedError, ServerClosedError,
    SingularMatrixError, SuperLUError)


class _Request:
    """One submitted right-hand side, possibly column-split over several
    micro-batches; completes when every column has been solved."""

    __slots__ = ("b", "k", "squeeze", "remaining", "parts", "error",
                 "t_submit", "t_deadline", "deadline_s", "slow_client_s",
                 "rungs", "event", "ctx")

    def __init__(self, b: np.ndarray, squeeze: bool):
        self.b = b
        self.k = b.shape[1]
        self.squeeze = squeeze
        self.remaining = self.k
        self.parts = []          # [(col offset, solved columns array)]
        self.error = None
        self.ctx = NULL_TICKET   # TicketContext when tracing is on
        self.t_submit = time.perf_counter()
        self.t_deadline = None   # absolute perf_counter expiry, or None
        self.deadline_s = 0.0
        self.slow_client_s = None    # chaos slow_client stall, or None
        self.rungs = []          # per-ticket recovery records (BERR gate)
        self.event = threading.Event()


class SolveTicket:
    """Handle for one submitted request (future-style)."""

    def __init__(self, req: _Request, server: "SolveServer"):
        self._req = req
        self._server = server

    def done(self) -> bool:
        return self._req.event.is_set()

    @property
    def rungs(self) -> list:
        """Per-ticket recovery actions taken for THIS request (e.g. the
        ``serve-ir`` BERR-gate rung) — the SolveReport analog of the
        serving tier."""
        return list(self._req.rungs)

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Block until the request's solve completes and return x with
        the submitted shape ((n,) stays (n,)).  Raises the request's
        structured error if it was shed/expired/poisoned or its batch
        dispatch failed, TimeoutError on expiry of ``timeout``.

        A request with an armed serving deadline is expired HERE too
        when the dispatcher is stalled: the waiter raises
        :class:`ServeDeadlineError` at its deadline instead of hanging
        until ``timeout``."""
        req = self._req
        if req.slow_client_s:        # chaos slow_client: stalled collector
            time.sleep(req.slow_client_s)
        end = None if timeout is None else time.perf_counter() + timeout
        while not req.event.is_set():
            now = time.perf_counter()
            if end is not None and now >= end:
                raise TimeoutError(
                    f"solve request ({req.k} columns) not served "
                    f"within {timeout}s")
            bounds = [] if end is None else [end - now]
            if req.t_deadline is not None:
                if now >= req.t_deadline:
                    # queued past the deadline: expire it ourselves (a
                    # no-op if the dispatcher carved it in-flight — the
                    # result is then imminent, keep polling briefly)
                    if not self._server._expire_request(req, now):
                        bounds = [min(bounds) if bounds else 0.05, 0.05]
                else:
                    bounds.append(req.t_deadline - now)
            req.event.wait(min(bounds) if bounds else None)
        if req.error is not None:
            raise req.error
        parts = sorted(req.parts, key=lambda p: p[0])
        x = (parts[0][1] if len(parts) == 1
             else np.concatenate([p[1] for p in parts], axis=1))
        return x[:, 0] if req.squeeze else x


class SolveServer:
    """Micro-batching solve service over one factored handle.

    Parameters
    ----------
    lu : LUFactorization
        A FACTORED handle (``lu.numeric`` present) — from a live
        ``gssvx`` call or ``persist.load_lu``.
    max_batch : int
        Micro-batch column cap; 0/None reads ``SLU_TPU_SERVE_MAX_BATCH``
        (whose 0 default means: the device solve's nrhs bucket cap).
    max_wait_s : float
        Coalescing window; None reads ``SLU_TPU_SERVE_MAX_WAIT_MS``.
    trans / conj :
        Serve ``AᵀX = B`` (``AᴴX = B``) through the same factors.
    queue_max : int
        Admission cap in pending COLUMNS; None reads
        ``SLU_TPU_SERVE_QUEUE_MAX`` (0 = unbounded).
    deadline_s : float
        Per-request serving deadline; None reads
        ``SLU_TPU_SERVE_DEADLINE_MS`` (0 = off).
    berr_max : float
        Per-request componentwise-berr quality gate; None reads
        ``SLU_TPU_SERVE_BERR_MAX`` (0 = off).  Needs the original
        matrix (``a=`` or a live handle carrying ``lu.a``).
    scrub_s : float
        Factor-integrity scrub period; None reads
        ``SLU_TPU_SERVE_SCRUB_S`` (0 = no background thread;
        :meth:`scrub_now` stays callable).
    a : SparseCSR
        The original matrix, for the BERR gate's residuals (defaults to
        ``lu.a`` — persist-loaded handles carry none).
    start : bool
        Spawn the dispatcher immediately; ``start=False`` lets tests
        enqueue a deterministic backlog first, then :meth:`start`.
    """

    def __init__(self, lu, max_batch: int | None = None,
                 max_wait_s: float | None = None, trans: bool = False,
                 conj: bool = False, start: bool = True,
                 queue_max: int | None = None,
                 deadline_s: float | None = None,
                 berr_max: float | None = None,
                 scrub_s: float | None = None, a=None):
        from superlu_dist_tpu.utils.options import env_float, env_int
        if lu is None or lu.numeric is None:
            raise SuperLUError(
                "SolveServer requires a FACTORED handle (lu.numeric is "
                "None — factor first, or load a persisted bundle via "
                "SolveServer.from_bundle)")
        self.lu = lu
        self.n = int(lu.n)
        self.trans = bool(trans)
        self.conj = bool(conj)
        self._solve = self._make_solve(lu)
        from superlu_dist_tpu.solve.plan import nrhs_buckets
        buckets = nrhs_buckets(env_int("SLU_TPU_SOLVE_NRHS_MAX"),
                               env_float("SLU_TPU_SOLVE_NRHS_GROWTH"))
        if not max_batch:
            max_batch = env_int("SLU_TPU_SERVE_MAX_BATCH")
        if not max_batch:
            max_batch = buckets[-1]     # the nrhs bucket cap
        self.max_batch = int(max_batch)
        # the batch sizes this server targets: the compiled nrhs buckets
        # up to (and always including) its own cap
        self._bucket_set = tuple(
            b for b in buckets if b < self.max_batch) + (self.max_batch,)
        if max_wait_s is None:
            max_wait_s = env_float("SLU_TPU_SERVE_MAX_WAIT_MS") / 1000.0
        self.max_wait_s = float(max_wait_s)
        # --- reliability knobs ------------------------------------------
        if queue_max is None:
            queue_max = env_int("SLU_TPU_SERVE_QUEUE_MAX")
        self.queue_max = int(queue_max)
        if deadline_s is None:
            deadline_s = env_float("SLU_TPU_SERVE_DEADLINE_MS") / 1000.0
        self.deadline_s = float(deadline_s)
        if berr_max is None:
            berr_max = env_float("SLU_TPU_SERVE_BERR_MAX")
        self._berr_max = float(berr_max)
        if scrub_s is None:
            scrub_s = env_float("SLU_TPU_SERVE_SCRUB_S")
        self.scrub_s = float(scrub_s)
        self._berr_op = None
        if self._berr_max > 0:
            if self.conj:
                raise SuperLUError(
                    "the serve BERR gate does not support conj servers "
                    "(residual needs an Aᴴ SpMV the gate does not build)")
            src = a if a is not None else lu.a
            if src is None:
                raise SuperLUError(
                    "SLU_TPU_SERVE_BERR_MAX needs the original matrix "
                    "for its residuals — pass a=..., or serve a live "
                    "handle that carries lu.a (persist bundles do not)")
            self._berr_op = src.transpose() if self.trans else src
        self.source = "live"
        # instrumented under SLU_TPU_VERIFY_LOCKS=1 (utils/lockwatch):
        # the condition shares the lock's identity — one mutex
        self._lock = make_lock("SolveServer._lock")
        self._cond = make_condition("SolveServer._cond", self._lock)
        # queue of [request, columns-already-taken] — a wide request
        # drains across batches without blocking narrower traffic
        self._queue: collections.deque = collections.deque()
        self._pending_cols = 0
        self._inflight = 0
        self._closed = False
        self._draining = False
        self._flush = False
        self._quarantine = None      # FactorCorruptError once scrub fails
        self._handle_epoch = 0
        self._digests = None         # per-front (sha_l, sha_u) baseline
        self._digest_source = "live handle (construction)"
        self._thread = None
        self._scrub_thread = None
        self._scrub_stop = threading.Event()
        # totals (under _lock); the metrics registry mirrors them when on
        self._requests = 0
        self._columns = 0
        self._batches = 0
        self._batch_cols = 0
        self._errors = 0
        self._shed = 0
        self._deadline_miss = 0
        self._poisoned = 0
        self._refined = 0
        self._swaps = 0
        self._refactors = 0
        self._scrub_runs = 0
        self._scrub_failures = 0
        self._metrics = m = get_metrics()
        self._metrics = m if m.enabled else None
        # latched once (the NULL_TRACER discipline): None when tracing
        # is off so submit pays one `is None` test and mints no context
        t = get_tracer()
        self._tracer = t if t.enabled else None
        self._accounter = get_accounter()    # always-on latency floor
        from superlu_dist_tpu.testing.chaos import get_serve_chaos
        self._chaos = get_serve_chaos()
        if self.scrub_s > 0:
            self._digests = self._compute_digests()
            self._scrub_thread = threading.Thread(
                target=self._scrub_loop, name="slu-serve-scrub",
                daemon=True)
            self._scrub_thread.start()
        if start:
            self.start()

    # ------------------------------------------------------------------
    @classmethod
    def from_bundle(cls, dirpath: str, **kw) -> "SolveServer":
        """Serve from a persisted LU bundle (persist/serial.save_lu):
        the handle loads digest-verified and solves with ZERO
        refactorization — the warm-start path a serving fleet restarts
        through (FACT time stays 0.0; docs/RELIABILITY.md).  The
        bundle's manifest digests become the scrub baseline — the
        DURABLE ground truth."""
        from superlu_dist_tpu.persist.serial import (bundle_front_digests,
                                                     load_lu)
        srv = cls(load_lu(dirpath), **kw)
        srv.source = str(dirpath)
        # the scrubber thread (scrub_s > 0) is already live here: the
        # digest re-base must happen under the lock it scans with
        # (SLU108); hash outside, assign inside
        digests = bundle_front_digests(dirpath)
        with srv._lock:
            srv._digests = digests
            srv._digest_source = f"bundle {dirpath}"
        return srv

    def _make_solve(self, lu):
        if self.trans:
            return lambda b: lu.solve_factored_trans(b, conj=self.conj)
        return lu.solve_factored

    # ------------------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="slu-serve-dispatch",
                daemon=True)
            self._thread.start()
        return self

    def submit(self, b: np.ndarray, parent=None) -> SolveTicket:
        """Enqueue one right-hand side — (n,) or (n, k), original
        labeling — and return its ticket immediately.  Admission control
        runs HERE: a closed server raises :class:`ServerClosedError`, a
        quarantined handle :class:`FactorCorruptError`, a draining or
        over-capacity queue sheds with :class:`ServeOverloadError`.

        ``parent`` is an optional parent trace context (a router-minted
        ``TicketContext`` or an ``obs.slo.parent_ref``): when tracing is
        on, the request's ``request``-category span chain joins the
        parent's trace id.  With all obs knobs unset and no parent, the
        request carries the shared ``NULL_TICKET`` singleton — zero
        per-submit allocation (enforced by check_trace_overhead.py)."""
        b = np.asarray(b)
        squeeze = b.ndim == 1
        b2 = b[:, None] if squeeze else b
        if b2.ndim != 2 or b2.shape[0] != self.n or b2.shape[1] == 0:
            raise SuperLUError(
                f"rhs shape {b.shape} does not fit an n={self.n} serve "
                "handle (need (n,) or (n, k>0))")
        k = b2.shape[1]
        m = self._metrics
        expired = ()
        try:
            with self._cond:
                if self._closed:
                    raise ServerClosedError("SolveServer is closed")
                if self._quarantine is not None:
                    q = self._quarantine
                    # dump=False: this re-raise of an already-reported
                    # quarantine performs NO postmortem I/O under the lock
                    raise FactorCorruptError(  # slulint: disable=SLU109
                        q.groups, q.source, dump=False)
                now = time.perf_counter()
                expired = self._expire_due_locked(now)
                if self._draining:
                    self._shed += 1
                    if m is not None:
                        m.inc("slu_serve_shed_total", 1.0,
                              reason="draining")
                    raise ServeOverloadError(k, self._pending_cols,
                                             self.queue_max,
                                             reason="draining")
                if self.queue_max > 0 and self._pending_cols + k > \
                        self.queue_max:
                    self._shed += 1
                    if m is not None:
                        m.inc("slu_serve_shed_total", 1.0,
                              reason="queue_full")
                    raise ServeOverloadError(k, self._pending_cols,
                                             self.queue_max)
                if self._chaos is not None:
                    b2 = self._chaos.poison_submit(b2, self._columns)
                req = _Request(b2, squeeze)
                if self.deadline_s > 0:
                    req.deadline_s = self.deadline_s
                    req.t_deadline = req.t_submit + self.deadline_s
                if self._chaos is not None and \
                        self._chaos.is_slow_client(self._requests):
                    req.slow_client_s = self._chaos.plan.secs
                self._queue.append([req, 0])
                self._pending_cols += req.k
                self._requests += 1
                self._columns += req.k
                depth = self._pending_cols
                if self._tracer is not None or (
                        parent is not None
                        and getattr(parent, "enabled", False)):
                    req.ctx = TicketContext(f"s{self._requests}",
                                            req.t_submit, parent)
                    req.ctx.note(nrhs=req.k)
                self._cond.notify_all()
        finally:
            # deadline postmortems (flight dump + span emit) run OUTSIDE
            # the lock — the SLU109 hold discipline
            self._deadline_postmortems(expired)
        if m is not None:
            m.inc("slu_serve_requests_total", 1.0)
            m.inc("slu_serve_columns_total", float(req.k))
            m.set("slu_serve_queue_depth", float(depth))
        return SolveTicket(req, self)

    def solve(self, b: np.ndarray,
              timeout: float | None = None) -> np.ndarray:
        """submit() + result(): the one-call convenience path."""
        return self.submit(b).result(timeout)

    def flush(self):
        """Dispatch whatever is pending without waiting out the
        coalescing window."""
        with self._cond:
            self._flush = True
            self._cond.notify_all()

    # ------------------------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Cooperative drain: reject new work (``ServeOverloadError``,
        reason ``draining``) while finishing everything already queued
        and in-flight.  Returns True once the queue and the in-flight
        batch are empty (False on ``timeout``).  The server stays alive
        — :meth:`swap` then :meth:`resume` is the refactor-on-degrade
        sequence; :meth:`close` the shutdown one."""
        end = None if timeout is None else time.perf_counter() + timeout
        with self._cond:
            self._draining = True
            self._flush = True
            self._cond.notify_all()
            while self._queue or self._inflight:
                if self._thread is None or not self._thread.is_alive():
                    # no dispatcher will ever serve these: deliver the
                    # structured shutdown error instead of stranding them
                    self._purge_queue_locked(
                        lambda req: ServerClosedError(
                            "SolveServer drained with no dispatcher — "
                            "request abandoned undelivered"))
                    return True
                left = None if end is None else end - time.perf_counter()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(left if left is not None else 0.5)
            return True

    def resume(self):
        """Lift drain mode: accept submissions again."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()
        return self

    def close(self, timeout: float | None = 10.0):
        """Stop accepting work, drain the queue, join the dispatcher —
        then deliver :class:`ServerClosedError` to every ticket still
        undelivered (a never-started or dead dispatcher cannot strand a
        waiter; the satellite fix for the submit/close race).

        The joins are BOUNDED by default (SLU110's canonical fix):
        interpreter shutdown must never race a live daemon against
        module teardown, so a wedged dispatcher is abandoned after
        ``timeout`` (its queued tickets still get their structured
        error) instead of hanging ``close()`` forever.  Pass
        ``timeout=None`` to wait indefinitely.

        Racing an in-flight :meth:`swap`: close WINS — a swap that has
        not installed its target by the time close takes the lock
        raises ``ServerClosedError`` and releases the target (see the
        swap docstring; tests/test_serve_robust.py pins the
        ordering)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._scrub_stop.set()
        if self._scrub_thread is not None:
            self._scrub_thread.join(1.0 if timeout is None else
                                    min(1.0, timeout))
            self._scrub_thread = None
        if self._thread is not None:
            self._thread.join(timeout)
        if self._thread is None or not self._thread.is_alive():
            with self._cond:
                self._purge_queue_locked(
                    lambda req: ServerClosedError(
                        "SolveServer closed before this request was "
                        "served"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def swap(self, lu_or_bundle) -> "SolveServer":
        """Atomically replace the factored handle between batches — the
        hot-swap path (refactor-on-degrade, scheduled refresh, or
        recovery from quarantine).  Accepts a live FACTORED
        ``LUFactorization`` or a persist-bundle path.  Queued and future
        requests are served by the new handle; the in-flight batch (if
        any) finishes on the old one — zero tickets dropped.  Clears a
        scrub quarantine and re-bases the scrub digests.

        Ordering contract vs :meth:`close` (the two linearize on the
        server lock): **close wins**.  A ``close()`` that takes the
        lock before the swap installs makes this call raise
        :class:`ServerClosedError` — the swap target is released, never
        installed, and every undelivered ticket gets its deterministic
        ``ServerClosedError`` from ``close()``'s purge.  A swap that
        installs first completes normally and the close then shuts the
        swapped server down the ordinary way."""
        from superlu_dist_tpu.persist.serial import (bundle_front_digests,
                                                     load_lu)
        source = None
        lu = lu_or_bundle
        if isinstance(lu_or_bundle, (str, os.PathLike)):
            source = str(lu_or_bundle)
            lu = load_lu(source)
        if lu is None or lu.numeric is None:
            raise SuperLUError(
                "swap() requires a FACTORED handle (lu.numeric present) "
                "or a persisted bundle path")
        if int(lu.n) != self.n:
            raise SuperLUError(
                f"swap() handle is n={int(lu.n)}, server is n={self.n} "
                "— a swapped handle must factor the same-sized system")
        solve = self._make_solve(lu)
        # the scrubber thread re-bases self._digests under the lock, so
        # even this presence test must hold it (SLU108); the digest
        # hashing itself stays OUTSIDE the lock (SLU109 hold discipline)
        with self._lock:
            rebase = self.scrub_s > 0 or self._digests is not None
        digests = None
        if rebase:
            digests = (bundle_front_digests(source) if source is not None
                       else self._compute_digests(lu))
        berr_op = self._berr_op
        if self._berr_max > 0 and lu.a is not None:
            berr_op = lu.a.transpose() if self.trans else lu.a
        with self._cond:
            if self._closed:
                # the close()/swap() ordering contract: close WINS.  The
                # freshly loaded/validated target is released (never
                # installed), and close()'s purge has already delivered
                # ServerClosedError to every undelivered ticket.
                raise ServerClosedError(
                    "swap() aborted: the server closed during the swap "
                    "(close wins — the swap target was released and all "
                    "queued tickets received ServerClosedError)")
            self.lu = lu
            self._solve = solve
            self._handle_epoch += 1
            self._quarantine = None
            self._digests = digests
            self._digest_source = (f"bundle {source}" if source is not None
                                   else "live handle (swap)")
            self._berr_op = berr_op
            if source is not None:
                self.source = source
            self._swaps += 1
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.inc("slu_serve_swaps_total", 1.0)
        return self

    # ------------------------------------------------------------------
    def refactor(self, new_values, canary_b=None,
                 berr_max=None) -> "SolveServer":
        """Same-pattern hot refactorization: re-run the numeric phase of
        the SERVED handle over ``new_values`` (a same-pattern SparseCSR,
        or a raw CSR data array in the original matrix's ordering) and
        :meth:`swap` the result in — symbolic, plan, and compiled
        programs all reused, zero tickets dropped.  The pipeline is the
        crash-consistent one from ``drivers.gssvx.refactor``: the shadow
        factorization runs against a COPY of the handle, is BERR-gated
        on a canary solve, and only an adopted shadow reaches the swap —
        a poisoned/singular refactor raises
        :class:`~superlu_dist_tpu.utils.errors.RefactorRollbackError`
        (or :class:`PatternMismatchError` on pattern drift) with the
        previous handle still serving every queued and future ticket."""
        import dataclasses

        from superlu_dist_tpu.drivers.gssvx import refactor as _refactor
        with self._lock:
            if self._closed:
                raise ServerClosedError("SolveServer is closed")
            live = self.lu
        # the shadow handle shares the (immutable) symbolic fact, plan,
        # and compiled executors with the live one; refactor() adopts
        # onto the shadow only, so in-flight batches keep the old panels
        shadow = dataclasses.replace(live)
        _refactor(shadow, new_values, canary_b=canary_b,
                  berr_max=berr_max)
        self.swap(shadow)
        with self._lock:
            self._refactors += 1
        if self._metrics is not None:
            self._metrics.inc("slu_serve_refactors_total", 1.0)
        return self

    # ------------------------------------------------------------------
    def _compute_digests(self, lu=None):
        from superlu_dist_tpu.persist.serial import front_digests
        return front_digests((lu or self.lu).numeric.fronts)

    def scrub_now(self) -> list:
        """One factor-integrity scrub pass: re-hash the handle's
        resident panel stacks and compare against the baseline digests
        (persist-bundle manifest for ``from_bundle`` servers,
        construction/swap-time hashes otherwise).  Returns [] when
        clean; on mismatch the handle is QUARANTINED — queued tickets
        get the :class:`FactorCorruptError`, future submits are
        refused until :meth:`swap` — and the error raises (with its
        flight-recorder postmortem already dumped)."""
        with self._lock:
            epoch = self._handle_epoch
            numeric = self.lu.numeric
            base = self._digests
        if self._chaos is not None:
            self._chaos.corrupt_resident_panel(numeric.fronts)
        from superlu_dist_tpu.persist.serial import front_digests
        cur = front_digests(numeric.fronts)
        m = self._metrics
        if base is None:
            # first manual scrub of an unarmed server: establish the
            # baseline (nothing to compare yet)
            with self._cond:
                if epoch == self._handle_epoch:
                    self._digests = cur
                    self._scrub_runs += 1
            if m is not None:
                m.inc("slu_serve_scrub_runs_total", 1.0)
            return []
        bad = [g for g, (c, b) in enumerate(zip(cur, base)) if c != b]
        # construct (and flight-dump) the error OUTSIDE the lock: the
        # postmortem write must not stall submit/dispatch on the server
        # lock (SLU109 hold discipline).  A swap racing the scrub makes
        # the dump a stale-handle artifact — rare, and still evidence.
        err = (FactorCorruptError(bad, source=self._digest_source)
               if bad else None)
        with self._cond:
            if epoch != self._handle_epoch:
                return []    # swapped mid-scrub: the scan is stale
            self._scrub_runs += 1
            if err is not None:
                self._quarantine = err
                self._scrub_failures += 1
                self._purge_queue_locked(lambda req: err)
                self._cond.notify_all()
        if m is not None:
            m.inc("slu_serve_scrub_runs_total", 1.0)
            if err is not None:
                m.inc("slu_serve_scrub_failures_total", 1.0)
        if err is not None:
            raise err
        return []

    def _scrub_loop(self):
        while not self._scrub_stop.wait(self.scrub_s):
            try:
                self.scrub_now()
            except FactorCorruptError:
                # quarantine installed + postmortem dumped; keep
                # scrubbing — a swap() re-bases the digests and the
                # next pass verifies the fresh handle
                pass
            except Exception:
                pass    # the scrubber must never kill the process

    # ------------------------------------------------------------------
    def idle(self) -> bool:
        """True when nothing is queued or in flight — the fleet handle
        cache's eviction predicate (serve/handlecache.py): only an idle
        server may be evicted, so eviction can never drop a ticket."""
        with self._lock:
            return not self._queue and not self._inflight

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Serving counters so far (process-local; the metrics registry
        carries the scrapeable twin)."""
        with self._lock:
            batches = self._batches
            return {
                "requests": self._requests,
                "columns": self._columns,
                "batches": batches,
                "errors": self._errors,
                "shed": self._shed,
                "deadline_miss": self._deadline_miss,
                "poisoned_columns": self._poisoned,
                "refined": self._refined,
                "swaps": self._swaps,
                "refactors": self._refactors,
                "scrub_runs": self._scrub_runs,
                "scrub_failures": self._scrub_failures,
                "queue_depth": self._pending_cols,
                "mean_batch_columns": (round(self._batch_cols / batches, 2)
                                       if batches else 0.0),
                "max_batch": self.max_batch,
                "max_wait_s": self.max_wait_s,
                "queue_max": self.queue_max,
                "deadline_s": self.deadline_s,
                "source": self.source,
                "closed": self._closed,
                "draining": self._draining,
                "quarantined": self._quarantine is not None,
            }

    # ------------------------------------------------------------------
    def _expire_request(self, req: _Request, now: float) -> bool:
        """Expire one deadline-missed request if it is still queued
        (called from its waiting ticket).  Returns True when the ticket
        was delivered its ServeDeadlineError (or had already been
        delivered something); False when the request is in-flight in a
        batch — the result is imminent and wins."""
        delivered = False
        with self._cond:
            if req.event.is_set():
                return True
            for entry in self._queue:
                if entry[0] is req:
                    self._queue.remove(entry)
                    self._pending_cols -= req.k - entry[1]
                    self._fail_expired_locked(req, now)
                    self._cond.notify_all()
                    delivered = True
                    break
        if delivered:
            self._deadline_postmortems([req])
        return delivered

    def _fail_expired_locked(self, req: _Request, now: float) -> None:
        ctx = req.ctx
        if ctx.enabled:
            # the whole budget went to the queue: one contiguous stage
            ctx.stage("queue_wait", req.t_submit, now - req.t_submit)
            ctx.note(deadline_s=req.deadline_s)
        # constructed under the lock: ServeDeadlineError does NO
        # postmortem I/O at construction — the caller invokes
        # flight_postmortem() outside the lock (_deadline_postmortems)
        req.error = ServeDeadlineError(req.deadline_s,
                                       now - req.t_submit, req.k,
                                       stages=ctx.stages_ms() or None)
        req.error.trace_id = ctx.trace_id
        req.event.set()
        self._deadline_miss += 1
        self._accounter.observe(req.k, now - req.t_submit)
        if self._metrics is not None:
            self._metrics.inc("slu_serve_deadline_miss_total", 1.0)

    def _expire_due_locked(self, now: float) -> list:
        """Under the lock: expire every queued request whose serving
        deadline has passed — expired work never reaches a batch, so a
        backlog of abandoned requests cannot starve live ones.  Returns
        the expired requests; the caller MUST hand them to
        ``_deadline_postmortems`` after releasing the lock."""
        if self.deadline_s <= 0:
            return []
        expired = [e for e in self._queue
                   if e[0].t_deadline is not None
                   and now >= e[0].t_deadline]
        if not expired:
            return []
        for entry in expired:
            req, off = entry
            self._queue.remove(entry)
            self._pending_cols -= req.k - off
            self._fail_expired_locked(req, now)
        self._cond.notify_all()
        return [e[0] for e in expired]

    def _deadline_postmortems(self, reqs) -> None:
        """OUTSIDE the lock (SLU109): flight-dump each expired request's
        ServeDeadlineError (stage timings attached) and emit its span
        chain so the deadline miss shows up on the Perfetto track."""
        if not reqs:
            return
        tracer = None
        for req in reqs:
            err = req.error
            if isinstance(err, ServeDeadlineError):
                err.flight_postmortem()
            ctx = req.ctx
            if ctx.enabled:
                if tracer is None:
                    tracer = get_tracer()
                ctx.emit(tracer, req.t_submit + getattr(
                    err, "waited_s", 0.0), status="deadline_miss")

    def _earliest_deadline_locked(self):
        due = [e[0].t_deadline for e in self._queue
               if e[0].t_deadline is not None]
        return min(due) if due else None

    def _purge_queue_locked(self, err_for) -> int:
        """Under the lock: deliver ``err_for(req)`` to every queued,
        undelivered ticket and empty the queue.  The shutdown /
        quarantine path — a ticket must always resolve to a result or a
        structured error, never a hang."""
        n = 0
        while self._queue:
            req, off = self._queue.popleft()
            self._pending_cols -= req.k - off
            if not req.event.is_set():
                req.error = err_for(req)
                req.event.set()
                n += 1
        self._pending_cols = max(self._pending_cols, 0)
        return n

    # ------------------------------------------------------------------
    def _take_batch(self):
        """Under the lock: carve up to max_batch columns off the queue
        head.  Returns [(request, req_lo, req_hi), ...] (empty on
        shutdown with a drained queue).  Requests already delivered an
        error (expired, poisoned in an earlier batch) are dropped."""
        segs = []
        total = 0
        while self._queue and total < self.max_batch:
            entry = self._queue[0]
            req, off = entry
            if req.event.is_set():       # expired/errored: nothing to do
                self._queue.popleft()
                self._pending_cols -= req.k - off
                continue
            take = min(req.k - off, self.max_batch - total)
            segs.append((req, off, off + take))
            total += take
            if off + take == req.k:
                self._queue.popleft()
            else:
                entry[1] = off + take
        self._pending_cols -= total
        return segs

    def _dispatch_loop(self):
        tracer = get_tracer()
        while True:
            expired = []
            with self._cond:
                while True:
                    now = time.perf_counter()
                    expired += self._expire_due_locked(now)
                    if self._quarantine is not None and self._queue:
                        q = self._quarantine
                        self._purge_queue_locked(
                            lambda req: FactorCorruptError(
                                q.groups, q.source, dump=False))
                    if self._queue:
                        break
                    if self._closed:
                        # exit via the empty-batch path below so the
                        # expired postmortems run OUTSIDE the lock
                        break
                    due = self._earliest_deadline_locked()
                    self._flush = False
                    self._cond.wait(None if due is None
                                    else max(due - now, 0.0))
                # coalescing: hold the oldest request open for the
                # batching window unless the batch can already fill (or
                # a flush/close/drain asked for immediacy).  t_co0 marks
                # the window's start — the queue_wait/coalesce stage
                # boundary for the requests this batch carves.
                t_co0 = time.perf_counter()
                deadline = t_co0 + self.max_wait_s
                while (self._pending_cols < self.max_batch
                       and not self._closed and not self._flush
                       and not self._draining
                       and self._quarantine is None):
                    now = time.perf_counter()
                    expired += self._expire_due_locked(now)
                    if not self._queue:
                        break
                    left = deadline - now
                    due = self._earliest_deadline_locked()
                    if due is not None:
                        left = min(left, due - now)
                    if left <= 0:
                        break
                    self._cond.wait(left)
                self._flush = False
                now = time.perf_counter()
                expired += self._expire_due_locked(now)
                segs = self._take_batch()
                depth = self._pending_cols
                solve_fn = self._solve    # swap-safe snapshot
                self._inflight = sum(hi - lo for _, lo, hi in segs)
            self._deadline_postmortems(expired)
            if not segs:
                with self._cond:
                    self._cond.notify_all()    # wake drain waiters
                    if self._closed and not self._queue:
                        return
                continue
            try:
                self._dispatch(segs, depth, tracer, solve_fn, t_co0)
            except Exception as e:     # noqa: BLE001 — the dispatcher
                for req, lo, hi in segs:       # must never die holding
                    if not req.event.is_set():  # undelivered tickets
                        req.error = e
                        req.event.set()
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()

    # ------------------------------------------------------------------
    def _bisect_bad(self, mat, solve_fn, lo, hi):
        """Find the poisoned columns of a batch whose WHOLE solve raised
        a numeric breakdown: bisect the column range until each failure
        is pinned to single columns (log₂(width) extra solves, only on
        the failure path)."""
        try:
            x = np.asarray(solve_fn(mat[:, lo:hi]))
        except (NumericBreakdownError, FloatingPointError):
            if hi - lo == 1:
                return [lo]
            mid = (lo + hi) // 2
            return (self._bisect_bad(mat, solve_fn, lo, mid)
                    + self._bisect_bad(mat, solve_fn, mid, hi))
        if x.ndim == 1:
            x = x[:, None]
        fin = np.isfinite(x).all(axis=0)
        return [lo + int(j) for j in np.nonzero(~fin)[0]]

    def _isolate(self, mat, solve_fn, exc):
        """A batch-level numeric failure: localize the offending columns
        and re-serve the healthy ones AT THE ORIGINAL BATCH WIDTH (the
        poisoned columns zeroed — benign), so the survivors' X is
        bit-identical to an unpoisoned dispatch of the same batch
        (per-column independence of the batched sweeps).  Returns
        (x, bad_column_indices); re-raises ``exc`` when the failure
        cannot be localized to columns."""
        bad = self._bisect_bad(mat, solve_fn, 0, mat.shape[1])
        if not bad:
            raise exc
        clean = np.array(mat, copy=True)
        clean[:, bad] = 0
        x = np.asarray(solve_fn(clean))
        fin = np.isfinite(x).all(axis=0)
        more = [int(j) for j in np.nonzero(~fin)[0] if j not in bad]
        if more:
            # columns that only break in the full-width dispatch: fold
            # them into the poisoned set and re-serve once more
            bad = sorted(set(bad) | set(more))
            clean[:, more] = 0
            x = np.asarray(solve_fn(clean))
            if not np.isfinite(np.delete(x, bad, axis=1)).all():
                raise exc       # not column-local after all
        return x, bad

    def _berr_gate(self, req, solve_fn):
        """Per-ticket residual quality gate (``SLU_TPU_SERVE_BERR_MAX``):
        a completing request whose componentwise berr exceeds the gate
        is routed through the per-ticket IR rung — its neighbors in the
        micro-batch are untouched."""
        from superlu_dist_tpu.refine.ir import refine_ticket
        parts = sorted(req.parts, key=lambda p: p[0])
        x = (parts[0][1] if len(parts) == 1
             else np.concatenate([p[1] for p in parts], axis=1))
        x2, before, after, adopted = refine_ticket(
            self._berr_op, req.b, x, solve_fn, self._berr_max)
        if before <= self._berr_max:
            return
        if adopted:
            req.parts = [(0, np.asarray(x2))]
        req.rungs.append({"rung": "serve-ir", "berr_before": before,
                          "berr_after": after, "adopted": adopted,
                          "target": self._berr_max})
        with self._lock:
            self._refined += 1
        if self._metrics is not None:
            self._metrics.inc("slu_serve_refined_total", 1.0)

    def _stage_prefix(self, ctx, req, t_co0, t0, td0, td1):
        """Record the shared stage prefix of a completing/poisoned
        request: queue_wait → coalesce → dispatch → device, contiguous
        from submit to the device-solve end (each stage starts where
        the previous one ended, so durations sum exactly)."""
        tc = min(max(t_co0, req.t_submit), t0)
        ctx.stage("queue_wait", req.t_submit, tc - req.t_submit)
        ctx.stage("coalesce", tc, t0 - tc)
        ctx.stage("dispatch", t0, td0 - t0)
        ctx.stage("device", td0, td1 - td0)

    def _dispatch(self, segs, depth, tracer, solve_fn, t_co0=None):
        cols = sum(hi - lo for _, lo, hi in segs)
        kb = bucket_nrhs(min(cols, self.max_batch), self._bucket_set)
        t0 = time.perf_counter()
        if t_co0 is None:
            t_co0 = t0
        m = self._metrics
        if m is not None:
            for req, lo, hi in segs:
                m.observe("slu_serve_queue_wait_seconds",
                          t0 - req.t_submit)
        if len(segs) == 1:
            req, lo, hi = segs[0]
            mat = req.b[:, lo:hi]
        else:
            dtype = np.result_type(*(s[0].b.dtype for s in segs))
            mat = np.empty((self.n, cols), dtype=dtype)
            c = 0
            for req, lo, hi in segs:
                mat[:, c:c + hi - lo] = req.b[:, lo:hi]
                c += hi - lo
        x, err, bad = None, None, ()
        td0 = time.perf_counter()      # dispatch/device stage boundary
        try:
            with tracer.span("serve-batch", cat="dispatch", columns=cols,
                             bucket=kb, requests=len(segs),
                             queue_depth=depth, trans=self.trans):
                x = np.asarray(solve_fn(mat))
            if not np.isfinite(x).all():
                # poisoned request(s): the healthy columns of THIS
                # result are already bit-exact (per-column independence)
                # — only the non-finite ones fail
                bad = [int(j) for j in
                       np.nonzero(~np.isfinite(x).all(axis=0))[0]]
        except NumericBreakdownError as e:
            try:
                x, bad = self._isolate(mat, solve_fn, e)
            except Exception as e2:     # noqa: BLE001
                x, err = None, e2
        except Exception as e:          # noqa: BLE001 — the error belongs
            x, err = None, e            # to the tickets, not the loop
        now = time.perf_counter()
        done_lat = []
        acct = self._accounter
        with self._lock:
            self._batches += 1
            self._batch_cols += cols
            if err is not None:
                self._errors += 1
            if bad:
                self._poisoned += len(bad)
        c = 0
        for req, lo, hi in segs:
            w = hi - lo
            if req.event.is_set():      # expired while in flight
                c += w
                continue
            ctx = req.ctx
            seg_bad = [j for j in bad if c <= j < c + w]
            if err is not None:
                req.error = err
                req.event.set()
            elif seg_bad:
                if ctx.enabled:
                    self._stage_prefix(ctx, req, t_co0, t0, td0, now)
                # constructed OUTSIDE the server lock: the flight dump
                # at construction carries the stage timings
                req.error = ServePoisonedError(
                    [lo + (j - c) for j in seg_bad], batch_columns=cols,
                    where="serve-batch",
                    stages=ctx.stages_ms() or None)
                req.error.trace_id = ctx.trace_id
                if ctx.enabled:
                    ctx.emit(tracer, now, status="poisoned")
                req.event.set()
            else:
                req.parts.append((lo, x[:, c:c + w]))
                req.remaining -= w
                if req.remaining == 0:
                    tref = now
                    if self._berr_max > 0:
                        self._berr_gate(req, solve_fn)
                        tref = time.perf_counter()
                    t_end = time.perf_counter()
                    lat = t_end - req.t_submit
                    if ctx.enabled:
                        self._stage_prefix(ctx, req, t_co0, t0, td0, now)
                        ctx.stage("refine", now, tref - now)
                        ctx.stage("deliver", tref, t_end - tref)
                        ctx.note(bucket=kb, batch_columns=cols,
                                 queue_depth=depth)
                        ctx.emit(tracer, t_end)
                    done_lat.append(lat)
                    acct.observe(req.k, lat)
                    req.event.set()
            c += w
        if m is not None:
            m.inc("slu_serve_batches_total", 1.0)
            m.set("slu_serve_queue_depth", float(depth))
            m.observe("slu_serve_batch_fill", cols / max(kb, 1))
            m.set("slu_serve_batch_seconds", now - t0)
            if err is not None:
                m.inc("slu_serve_errors_total", 1.0,
                      error=type(err).__name__)
            if bad:
                m.inc("slu_serve_poisoned_total", float(len(bad)))
            for lat in done_lat:
                m.observe("slu_serve_request_seconds", lat)
