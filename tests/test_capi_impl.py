"""Python-level tests of the C-API implementation layer (capi_impl.py).

The C client (test_capi.c, slow tier) exercises the same surface through
the embedded interpreter; this fast-tier twin drives the marshalling and
registry logic directly — options keys/values, the reuse tiers, strided
column-major RHS buffers, statistics, and the error-code contract
(-3 bad handle / -5 unknown key / -6 bad value; slu_tpu.h)."""

import ctypes

import numpy as np
import pytest

from superlu_dist_tpu.bindings import capi_impl as ci


def _tridiag(n=40):
    indptr = [0]
    indices = []
    values = []
    for i in range(n):
        if i > 0:
            indices.append(i - 1)
            values.append(-1.0)
        indices.append(i)
        values.append(4.0)
        if i < n - 1:
            indices.append(i + 1)
            values.append(-1.0)
        indptr.append(len(indices))
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int64),
            np.asarray(values, np.float64))


def _ptr(a):
    return a.ctypes.data


def test_options_registry_contract():
    h = ci.opt_create()
    assert ci.opt_set(h, "ColPerm", "COLAMD") == 0
    assert ci.opt_get(h, "ColPerm") == "COLAMD"
    assert ci.opt_set(h, "Trans", "TRANS") == 0
    assert ci.opt_set(h, "Equil", "NO") == 0
    assert ci.opt_get(h, "Equil") == "NO"
    assert ci.opt_set(h, "relax", "12") == 0
    assert ci.opt_get(h, "relax") == "12"
    assert ci.opt_set(h, "ParSymbFact", "YES") == 0
    assert ci.opt_get(h, "ParSymbFact") == "YES"
    assert ci.opt_set(h, "NoSuchKey", "1") == ci._BAD_KEY
    assert ci.opt_set(h, "ColPerm", "NOT_AN_ORDERING") == ci._BAD_VALUE
    assert ci.opt_set(999_999, "Equil", "NO") == ci._BAD_HANDLE
    assert ci.opt_get(999_999, "Equil") == ci._BAD_HANDLE
    assert ci.opt_get(h, "NoSuchKey") == ci._BAD_KEY
    assert ci.opt_free(h) == 0
    assert ci.opt_free(h) == ci._BAD_HANDLE


def test_factor_refactor_solve_stats_strided():
    n = 40
    indptr, indices, values = _tridiag(n)
    xt = 1.0 + 0.01 * np.arange(n)
    b = np.zeros(n)
    for i in range(n):
        for k in range(indptr[i], indptr[i + 1]):
            b[i] += values[k] * xt[indices[k]]

    info, h = ci.factor_opts(0, n, len(values), _ptr(indptr),
                             _ptr(indices), _ptr(values))
    assert info == 0 and h > 0

    # strided 2-RHS column-major buffers (ld > n)
    ld = n + 5
    b2 = np.zeros((ld, 2), order="F")
    x2 = np.zeros((ld, 2), order="F")
    b2[:n, 0] = b
    b2[:n, 1] = 3.0 * b
    rc = ci.solve_factored_opts(h, 0, n, _ptr(b2), ld, _ptr(x2), ld, 2)
    assert rc == 0
    assert np.max(np.abs(x2[:n, 0] - xt)) < 1e-10
    assert np.max(np.abs(x2[:n, 1] - 3.0 * xt)) < 1e-10
    assert np.all(x2[n:] == 0.0)          # padding rows untouched
    # undersized ldx is rejected BEFORE solving
    assert ci.solve_factored_opts(h, 0, n, _ptr(b2), ld, _ptr(x2),
                                  n - 1, 2) == ci._BAD_VALUE

    # SamePattern refactor with scaled values
    v2 = 2.0 * values
    assert ci.refactor(h, len(v2), _ptr(v2), 1) == 0
    rc = ci.solve_factored_opts(h, 0, n, _ptr(b2), ld, _ptr(x2), ld, 2)
    assert rc == 0
    assert np.max(np.abs(x2[:n, 0] - 0.5 * xt)) < 1e-10
    # wrong nnz / bad tier
    assert ci.refactor(h, len(v2) - 1, _ptr(v2), 1) == ci._BAD_VALUE
    assert ci.refactor(h, len(v2), _ptr(v2), 7) == ci._BAD_VALUE
    assert ci.refactor(12345, len(v2), _ptr(v2), 1) == ci._BAD_HANDLE

    # statistics
    assert ci.stat_get(h, "FACT") >= 0.0
    assert ci.stat_get(h, "NNZ_L") >= n
    assert np.isnan(ci.stat_get(h, "NoSuchStat"))
    assert ci.stat_get(4242, "FACT") == ci._BAD_HANDLE

    assert ci.free(h) == 0
    assert ci.free(h) == ci._BAD_HANDLE


def test_one_shot_solve_with_options():
    n = 40
    indptr, indices, values = _tridiag(n)
    b = np.ones(n)
    x = np.zeros(n)
    h = ci.opt_create()
    assert ci.opt_set(h, "IterRefine", "SLU_DOUBLE") == 0
    rc = ci.solve_opts(h, n, len(values), _ptr(indptr), _ptr(indices),
                       _ptr(values), _ptr(b), n, _ptr(x), n, 1)
    assert rc == 0
    # residual check
    r = b.copy()
    for i in range(n):
        for k in range(indptr[i], indptr[i + 1]):
            r[i] -= values[k] * x[indices[k]]
    assert np.max(np.abs(r)) < 1e-12
    assert ci.opt_free(h) == 0
