"""SLU101 — collective-consistency.

Every rank attached to a TreeComm domain must execute the same collective
sequence (treecomm.py's contract; the reference's per-supernode Bc/Rd
trees are likewise matched, TreeBcast_slu.hpp).  The deadly shapes are
lexically recognizable:

* a collective call INSIDE a branch (or loop) whose condition depends on
  the caller's rank / grid coordinates — only some ranks reach it;
* a collective call AFTER a rank-conditioned early exit (`return` /
  `raise` / `break` / `continue` under a rank test, or an `assert` whose
  predicate involves the rank) earlier in the same function — some ranks
  left before reaching it;
* a collective call inside an `except` handler — exceptions raise on a
  strict subset of ranks by construction (the project-blessed pattern is
  pgssvx.bcast_result, which ships the exception THROUGH a collective
  every rank reaches).

The rule is lexical per function; nested `def`s start a fresh context
(their bodies run at call time, not at definition time).
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Rule

COLLECTIVE_METHODS = frozenset({
    "bcast", "reduce_sum", "allreduce_sum", "bcast_bytes", "bcast_obj",
    "bcast_any", "reduce_sum_any", "allreduce_sum_any",
})

_RANK_ATTRS = frozenset({"rank", "iam", "myrow", "mycol"})
_RANK_NAMES = frozenset({"rank", "iam", "myrank", "my_rank"})


def _is_rank_expr(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _RANK_ATTRS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _RANK_NAMES:
            return True
    return False


def _collective_calls(node: ast.AST):
    """Collective Call nodes lexically inside `node`, excluding nested
    function/class bodies (those execute in their own context)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr in COLLECTIVE_METHODS:
                yield child
            stack.append(child)


def _has_early_exit(stmts) -> bool:
    for st in stmts:
        for sub in ast.walk(st):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, (ast.Return, ast.Raise, ast.Break,
                                ast.Continue)):
                return True
    return False


class _FunctionScan:
    """One function body, scanned statement-by-statement in order."""

    def __init__(self, rule, path, findings):
        self.rule = rule
        self.path = path
        self.findings = findings
        self.diverged_at = None    # line of the earliest rank-dep. exit

    def flag(self, call, why):
        self.findings.append(self.rule.finding(self.path, call, why))

    def scan(self, stmts, in_rank_branch=False, in_except=False):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionScan(self.rule, self.path, self.findings) \
                    .scan(st.body)
                continue
            if isinstance(st, ast.ClassDef):
                self.scan(st.body, in_rank_branch, in_except)
                continue

            rank_cond = isinstance(st, (ast.If, ast.While)) \
                and _is_rank_expr(st.test)

            # flag the collectives this statement directly owns (for
            # compound statements that is the header expression, which
            # every rank still evaluates — so rank_cond alone does not
            # flag it; only an ENCLOSING rank branch does)
            for call in self.direct_collectives(st):
                if in_except:
                    self.flag(call,
                              "collective inside an `except` handler — "
                              "the exception raised on a subset of ranks, "
                              "so the others never reach this call")
                elif in_rank_branch:
                    self.flag(call,
                              "collective under rank-dependent control "
                              "flow — only some ranks reach it")
                elif self.diverged_at is not None:
                    self.flag(call,
                              "collective after a rank-dependent early "
                              f"exit (line {self.diverged_at}) — ranks "
                              "that exited never reach this call")

            # recurse into compound statements with updated context
            if isinstance(st, (ast.If, ast.While)):
                branch = in_rank_branch or rank_cond
                self.scan(st.body, branch, in_except)
                self.scan(st.orelse, branch, in_except)
                if rank_cond and not in_rank_branch \
                        and self.diverged_at is None \
                        and (_has_early_exit(st.body)
                             or _has_early_exit(st.orelse)):
                    self.diverged_at = st.lineno
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self.scan(st.body, in_rank_branch, in_except)
                self.scan(st.orelse, in_rank_branch, in_except)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self.scan(st.body, in_rank_branch, in_except)
            elif isinstance(st, ast.Try):
                self.scan(st.body, in_rank_branch, in_except)
                for h in st.handlers:
                    self.scan(h.body, in_rank_branch, True)
                self.scan(st.orelse, in_rank_branch, in_except)
                self.scan(st.finalbody, in_rank_branch, in_except)
            elif isinstance(st, ast.Assert) and _is_rank_expr(st.test) \
                    and not in_rank_branch and self.diverged_at is None:
                # an assert on a rank-dependent predicate is a
                # conditional raise on a subset of ranks
                self.diverged_at = st.lineno

    @staticmethod
    def direct_collectives(st):
        """Collectives in `st`'s own expressions — for compound
        statements, only the header (test/iter/items), since the body is
        scanned recursively with its own context."""
        if isinstance(st, (ast.If, ast.While)):
            roots = [st.test]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            roots = [st.iter]
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in st.items]
        elif isinstance(st, ast.Try):
            roots = []
        else:
            roots = [st]
        out = []
        for r in roots:
            if isinstance(r, ast.Call) and isinstance(r.func, ast.Attribute)\
                    and r.func.attr in COLLECTIVE_METHODS:
                out.append(r)
            out.extend(_collective_calls(r))
        return out


class CollectiveRule(Rule):
    rule_id = "SLU101"
    title = "collective-consistency"
    hint = ("make every rank reach the collective: hoist it out of the "
            "rank branch, allreduce the predicate first, or ship the "
            "root-side work through pgssvx.bcast_result (which carries "
            "exceptions to every rank)")

    def check(self, tree, source, path):
        findings = []
        # module level counts as one function body (scripts run it)
        _FunctionScan(self, path, findings).scan(tree.body)
        return findings
