#!/usr/bin/env bash
# NaN-guard smoke run: execute the numeric-core and driver test families
# under JAX_DEBUG_NANS=1, which makes XLA raise the moment any jitted
# computation PRODUCES a NaN.  Healthy inputs must never do so; a failure
# here means a kernel regressed into relying on NaN propagation.
#
# Tests that *intentionally* create NaN/Inf are deselected:
#   - singular systems factored with replace_tiny_pivot=False (the info>0
#     path deliberately lets a zero pivot propagate), and
#   - the known-failing zdf64 end-to-end case (pre-existing, BASELINE.md).
# The recovery suite's NaN-poisoned sentinel tests live in
# tests/test_recovery.py and are excluded wholesale for the same reason.
#
# One gate of scripts/ci_gates.sh (the consolidated CI entry point);
# ~1-2 min on CPU.  Gate contract (shared with run_slulint.sh,
# check_trace_overhead.py and check_verify_overhead.py): exits non-zero
# on ANY regression — here pytest's own exit code under `set -e`
# propagates a single NaN-producing test.
set -euo pipefail
cd "$(dirname "$0")/.."

exec env JAX_PLATFORMS=cpu JAX_DEBUG_NANS=1 \
  python -m pytest tests/test_gssvx.py tests/test_dense_ops.py \
  tests/test_device_solve.py tests/test_df64.py \
  -q -m 'not slow' -p no:cacheprovider \
  --deselect tests/test_gssvx.py::test_exact_singularity_reported_without_replacement \
  --deselect tests/test_df64.py::test_zdf64_complex_factorization_end_to_end \
  "$@"
