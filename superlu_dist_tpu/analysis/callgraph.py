"""Package-wide call graph for the dataflow-aware slulint rules.

PR-3's rules were purely lexical: SLU101 could only see a collective
call spelled directly inside the rank-conditioned branch, SLU103 only a
32-bit constructor assigned straight into an accumulator name.  The
deadly instances in a real tree hide behind one level of indirection —
a `_ship(tc, x)` wrapper whose body does the `bcast_any`, an `_alloc(n)`
helper whose `return np.zeros(n, dtype=np.int32)` flows into an indptr.
MPI tooling (MUST) long ago established that collective matching needs
whole-program reasoning; this module provides the static half.

The graph is *module-qualified*: every function definition in the
scanned tree gets a dotted qname (``superlu_dist_tpu.parallel.pgssvx.
pgssvx``, ``bench._main``, nested defs as ``mod.outer.inner``, methods
as ``mod.Class.method``), imports are resolved to qnames, and every
``Call`` node is resolved where a sound target exists:

* plain names — nested defs in scope, module-level functions, imported
  names (``from m import f`` / ``import m as alias`` + ``alias.f``);
* ``self.method(...)`` — the enclosing class, then its bases
  (project-resolved, e.g. ``FaultyTreeComm`` -> ``TreeComm``);
* ``obj.method(...)`` — when ``obj``'s class is known from a parameter
  annotation (``tc: TreeComm``), a local ``obj = ClassName(...)``
  constructor, or a call to a function whose returns are a single known
  class (``make_treecomm`` -> ``TreeComm``).

Unresolvable calls stay unresolved — the rules treat them as opaque
(false-negative-leaning, the slulint contract).  Resolution results are
stored per path keyed by the Call node's (line, col), so rules can look
up *their own* parse of the same source without sharing AST objects.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from superlu_dist_tpu.analysis.core import dotted_name


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition in the project."""

    qname: str
    name: str
    path: str
    module: str
    node: object                       # ast.FunctionDef | AsyncFunctionDef
    cls: str | None = None             # owning class qname for methods
    parent: str | None = None          # enclosing function qname (nested)
    children: dict = dataclasses.field(default_factory=dict)  # name->qname
    calls: list = dataclasses.field(default_factory=list)     # callee qnames

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)


@dataclasses.dataclass
class ClassInfo:
    qname: str
    name: str
    module: str
    bases: list = dataclasses.field(default_factory=list)     # raw dotted
    methods: dict = dataclasses.field(default_factory=dict)   # name->qname


@dataclasses.dataclass
class ModuleInfo:
    name: str
    path: str
    tree: object
    imports: dict = dataclasses.field(default_factory=dict)   # local->qname
    import_modules: dict = dataclasses.field(default_factory=dict)
    functions: dict = dataclasses.field(default_factory=dict)  # name->qname
    classes: dict = dataclasses.field(default_factory=dict)    # name->qname


def module_name_for_path(path: str) -> str:
    """Dotted module name for a scanned file.  Files under the package
    tree get their importable name; scripts/examples/bench get a
    path-derived one; anything else falls back to the stem (single-file
    fixture scans)."""
    parts = list(os.path.normpath(path).split(os.sep))
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if "superlu_dist_tpu" in parts:
        parts = parts[parts.index("superlu_dist_tpu"):]
    else:
        parts = [p for p in parts if p not in ("", ".", "..", os.sep)][-2:]
    return ".".join(parts) or "module"


class Project:
    """The call graph + per-path call resolution + dataflow summaries
    (the summaries themselves are filled in by analysis.dataflow)."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.by_path: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # per-path {(line, col) of a Call node: callee qname}
        self.call_sites: dict[str, dict] = {}
        # per-path {(line, col) of a FunctionDef: qname}
        self.func_sites: dict[str, dict] = {}
        # filled by dataflow.summarize(project)
        self.summaries: dict = {}

    # ---- lookups used by the rules -------------------------------------
    def call_target(self, path: str, call: ast.Call):
        """Resolved callee qname for a Call node of the rule's own parse
        of `path` (position-keyed), or None."""
        return self.call_sites.get(path, {}).get(
            (call.lineno, call.col_offset))

    def func_at(self, path: str, fn: ast.AST):
        qn = self.func_sites.get(path, {}).get(
            (fn.lineno, fn.col_offset))
        return self.functions.get(qn) if qn else None

    def summary(self, qname: str):
        return self.summaries.get(qname)

    def call_summary(self, path: str, call: ast.Call):
        qn = self.call_target(path, call)
        return self.summaries.get(qn) if qn else None


# --------------------------------------------------------------------------
# construction
# --------------------------------------------------------------------------

def build_project(sources: dict) -> Project:
    """sources: {path: source} or {path: (source, tree)} — parse errors
    are skipped (the driver reports them as SLU100 separately)."""
    proj = Project()
    for path, src in sources.items():
        if isinstance(src, tuple):
            source, tree = src
        else:
            source, tree = src, None
        if tree is None:
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue
        _index_module(proj, path, tree)
    for mod in proj.modules.values():
        _resolve_imports(proj, mod)
    for mod in proj.modules.values():
        _resolve_calls(proj, mod)
    from superlu_dist_tpu.analysis import dataflow
    dataflow.summarize(proj)
    return proj


def _index_module(proj: Project, path: str, tree: ast.AST) -> None:
    name = module_name_for_path(path)
    if name in proj.modules:        # same-named module: last one wins for
        name = name + "@" + path    # by-name lookup, keep both by path
    mod = ModuleInfo(name=name, path=path, tree=tree)
    proj.modules[name] = mod
    proj.by_path[path] = mod
    proj.call_sites.setdefault(path, {})
    proj.func_sites.setdefault(path, {})

    def add_func(node, parent_q, cls_q):
        q = f"{parent_q}.{node.name}"
        fi = FuncInfo(qname=q, name=node.name, path=path, module=name,
                      node=node, cls=cls_q)
        proj.functions[q] = fi
        proj.func_sites[path][(node.lineno, node.col_offset)] = q
        return fi

    def walk_body(body, parent_q, cls_q=None, parent_fi=None):
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = add_func(st, parent_q, cls_q)
                if parent_fi is not None:
                    fi.parent = parent_fi.qname
                    parent_fi.children[st.name] = fi.qname
                if cls_q is not None:
                    proj.classes[cls_q].methods[st.name] = fi.qname
                elif parent_fi is None:
                    mod.functions[st.name] = fi.qname
                walk_body(st.body, fi.qname, None, fi)
            elif isinstance(st, ast.ClassDef):
                cq = f"{parent_q}.{st.name}"
                ci = ClassInfo(qname=cq, name=st.name, module=name,
                               bases=[dotted_name(b) for b in st.bases
                                      if dotted_name(b)])
                proj.classes[cq] = ci
                if parent_fi is None and cls_q is None:
                    mod.classes[st.name] = cq
                walk_body(st.body, cq, cq, None)

    walk_body(tree.body, name)


def _resolve_imports(proj: Project, mod: ModuleInfo) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                mod.import_modules[local] = target
        elif isinstance(node, ast.ImportFrom):
            if node.level:      # relative: anchor on this module's package
                base_parts = mod.name.split(".")[:-node.level]
                base = ".".join(base_parts + ([node.module]
                                              if node.module else []))
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mod.imports[local] = f"{base}.{alias.name}" if base \
                    else alias.name


def _class_of_callable(proj: Project, qname: str):
    """If `qname` names a class, or a function whose returns are all one
    known class's constructor, that class's qname."""
    if qname in proj.classes:
        return qname
    fi = proj.functions.get(qname)
    if fi is None:
        return None
    rets = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Call):
                t = _lookup_name(proj, proj.modules[fi.module], fi,
                                 dotted_name(node.value.func))
                rets.add(t if t in proj.classes else None)
            else:
                rets.add(None)
    rets.discard(None)
    return rets.pop() if len(rets) == 1 else None


def _lookup_name(proj: Project, mod: ModuleInfo, fi, dotted: str):
    """Resolve a dotted name used inside function `fi` (or at module
    level when fi is None) to a project qname, or None."""
    if not dotted:
        return None
    head, _, rest = dotted.partition(".")
    # nested defs visible in the enclosing-function chain
    cur = fi
    while cur is not None:
        if head in cur.children and not rest:
            return cur.children[head]
        cur = proj.functions.get(cur.parent) if cur.parent else None
    # module-level defs
    if head in mod.functions and not rest:
        return mod.functions[head]
    if head in mod.classes:
        cq = mod.classes[head]
        return _class_member(proj, cq, rest) if rest else cq
    # imported names
    if head in mod.imports:
        target = mod.imports[head]
        return _qualify(proj, target, rest)
    if head in mod.import_modules:
        target = mod.import_modules[head]
        return _qualify(proj, target, rest) if rest else None
    return None


def _qualify(proj: Project, base: str, rest: str):
    q = f"{base}.{rest}" if rest else base
    if q in proj.functions or q in proj.classes:
        return q
    # `import pkg.mod` + `pkg.mod.Class.method`-style chains
    if rest and q.rsplit(".", 1)[0] in proj.classes:
        return _class_member(proj, q.rsplit(".", 1)[0], q.rsplit(".", 1)[1])
    # target module might itself re-export; give the dotted name back so
    # semantic special-cases (env helpers) can match by suffix
    return q


def _class_member(proj: Project, cls_q: str, member: str, _depth=0):
    """Method lookup with base-class resolution (bounded)."""
    if _depth > 8 or not member:
        return None
    ci = proj.classes.get(cls_q)
    if ci is None:
        return None
    head, _, rest = member.partition(".")
    if head in ci.methods and not rest:
        return ci.methods[head]
    mod = proj.modules.get(ci.module)
    for base in ci.bases:
        bq = _lookup_name(proj, mod, None, base) if mod else None
        if bq and bq in proj.classes:
            hit = _class_member(proj, bq, member, _depth + 1)
            if hit:
                return hit
    return None


def _annotation_class(proj, mod, fi, ann):
    """Class qname for a parameter/variable annotation node."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value
    else:
        name = dotted_name(ann)
    if not name:
        return None
    q = _lookup_name(proj, mod, fi, name)
    return q if q in proj.classes else None


def _var_classes(proj: Project, mod: ModuleInfo, fi: FuncInfo) -> dict:
    """Local-variable -> class-qname map for one function: parameter
    annotations, `x = ClassName(...)` constructors, and calls to
    functions returning a single known class."""
    out = {}
    node = fi.node
    a = node.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        cq = _annotation_class(proj, mod, fi, arg.annotation)
        if cq:
            out[arg.arg] = cq
    if fi.cls is not None and (a.posonlyargs + a.args):
        out.setdefault((a.posonlyargs + a.args)[0].arg, fi.cls)
    for st in ast.walk(node):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and st is not node:
            continue
        targets = []
        value = None
        if isinstance(st, ast.Assign):
            targets = [t.id for t in st.targets if isinstance(t, ast.Name)]
            value = st.value
        elif isinstance(st, ast.AnnAssign) and isinstance(st.target,
                                                          ast.Name):
            targets = [st.target.id]
            cq = _annotation_class(proj, mod, fi, st.annotation)
            if cq:
                out[st.target.id] = cq
            value = st.value
        if not targets or not isinstance(value, ast.Call):
            continue
        callee = _lookup_name(proj, mod, fi, dotted_name(value.func))
        cq = _class_of_callable(proj, callee) if callee else None
        if cq:
            for t in targets:
                out[t] = cq
    return out


def _resolve_calls(proj: Project, mod: ModuleInfo) -> None:
    for q, fi in list(proj.functions.items()):
        if fi.module != mod.name:
            continue
        var_cls = _var_classes(proj, mod, fi)
        for node in _own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            target = _resolve_one_call(proj, mod, fi, var_cls, node)
            if target is None:
                continue
            fi.calls.append(target)
            proj.call_sites[fi.path][(node.lineno, node.col_offset)] = \
                target
    # module-level calls (scripts run them)
    for node in _module_level_nodes(mod.tree):
        if isinstance(node, ast.Call):
            target = _resolve_one_call(proj, mod, None, {}, node)
            if target is not None:
                proj.call_sites[mod.path][(node.lineno,
                                           node.col_offset)] = target


def _own_nodes(fn):
    """Every node lexically inside `fn`, nested defs included (calls in
    a nested def still resolve in the enclosing module scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _module_level_nodes(tree):
    stack = [st for st in tree.body
             if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef))]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


def _resolve_one_call(proj, mod, fi, var_cls, call: ast.Call):
    func = call.func
    if isinstance(func, ast.Name):
        return _lookup_name(proj, mod, fi, func.id)
    if isinstance(func, ast.Attribute):
        # receiver-typed method call: self/annotated/constructed var
        if isinstance(func.value, ast.Name):
            recv = func.value.id
            cq = var_cls.get(recv)
            if cq is None and recv == "self" and fi is not None \
                    and fi.cls is not None:
                cq = fi.cls
            if cq is not None:
                hit = _class_member(proj, cq, func.attr)
                if hit:
                    return hit
        # dotted module path (mod.f / pkg.mod.Class(...))
        return _lookup_name(proj, mod, fi, dotted_name(func))
    return None
