"""SLU115 true-positive fixture (implicit downcast): a value-carrying
f32 panel is narrowed to bf16 and the narrowed array feeds a GEMM — the
compute dtype silently lost bits on the way to the MXU.  The witness
chain in the finding names both the cast line and the consuming call."""
import jax.numpy as jnp


def schur_update(panel, piv):
    p32 = panel.astype(jnp.float32)
    lo = p32.astype(jnp.bfloat16)          # flagged: f32 -> bf16
    return jnp.matmul(lo, piv, preferred_element_type=jnp.float32)


def half_entry(vals, sel):
    # flagged even with no visible provenance: a 16-bit target is a
    # presumed downcast of the compute dtype
    return jnp.dot(vals.astype(jnp.float16), sel,
                   preferred_element_type=jnp.float32)
