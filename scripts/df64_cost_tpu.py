#!/usr/bin/env python
"""Measure the df64 (double-float) factorization cost ratio vs f32 on the
real accelerator — PLAN.md §3/§4: the VPU-emulated ~2^-48 path is expected
at ~20-30 f32 flops per MAC; this pins the measured ratio and the df64
residual with refinement off (raw factor quality).

Prints one JSON line per size and appends to docs/df64_cost_tpu.jsonl.
Warm timings (executors cached, SamePattern tier).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()
    import jax.numpy as jnp

    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.numeric.df64_factor import get_df64_executor
    from superlu_dist_tpu.ops.df64 import df64_from_f64

    backend = jax.default_backend()
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "df64_cost_tpu.jsonl")
    sizes = tuple(int(s) for s in
                  os.environ.get("DF64_NX", "12,16,20").split(","))
    for nx in sizes:
        a = poisson3d(nx)
        n = a.n_rows
        sym = symmetrize_pattern(a)
        col_order = get_perm_c(Options(), a, sym)
        sf = symbolic_factorize(sym, col_order, relax=256,
                                max_supernode=1024)
        plan = build_plan(sf, min_bucket=32, growth=1.3)
        avals64 = sym.data[sf.value_perm].astype(np.float64)
        thresh = np.sqrt(np.finfo(np.float32).eps) * a.norm_max()

        ex32 = StreamExecutor(plan, "float32")
        a32 = jnp.asarray(avals64, jnp.float32)
        t32 = jnp.asarray(thresh, jnp.float32)
        out = ex32(a32, t32)
        jax.block_until_ready(out[0])
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = ex32(a32, t32)
            jax.block_until_ready(out[0])
            reps.append(time.perf_counter() - t0)
        f32_s = min(reps)

        exd = get_df64_executor(plan)
        ah, al = df64_from_f64(jnp.asarray(avals64))
        outd = exd((ah, al), jnp.asarray(thresh, jnp.float32))
        jax.block_until_ready(outd[0])
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            outd = exd((ah, al), jnp.asarray(thresh, jnp.float32))
            jax.block_until_ready(outd[0])
            reps.append(time.perf_counter() - t0)
        df64_s = min(reps)

        rec = {"n": n, "backend": backend,
               "f32_factor_seconds": round(f32_s, 5),
               "df64_factor_seconds": round(df64_s, 5),
               "cost_ratio": round(df64_s / max(f32_s, 1e-12), 2),
               "flops": plan.flops}
        print(json.dumps(rec), flush=True)
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
