"""Multi-handle residency — an LRU of factored handles per replica.

One ``SolveServer`` owns one factored handle; the fleet's traffic shape
(ROADMAP item 4, the arXiv:1909.04539 many-small-systems class) is a
MIXED stream of matrices keyed by persist bundle.  This cache gives a
replica that mixed-stream capability without refactoring anything:

* handles load **zero-refactor** through ``SolveServer.from_bundle``
  (persist/serial.load_lu — digest-verified, FACT time stays 0.0), and
  every load/reload is **scrub-verified**: one ``scrub_now()`` pass
  compares the freshly resident panel stacks against the bundle
  manifest's sha256 digests before the handle serves a single column;
* residency is budgeted in BYTES (``SLU_TPU_FLEET_HANDLE_BYTES``)
  using the manifest's byte ledger via the ``persist.lu_meta`` cheap
  peek — the cost of admitting a handle is known BEFORE paying the
  load;
* eviction is least-recently-used over **idle** servers only
  (``SolveServer.idle()``), so evicting a handle can never drop a
  ticket; a cache whose resident handles are all busy is allowed to
  run over budget rather than lose work (the zero-loss discipline);
* an evicted key reloads transparently on its next ``get`` — the
  reload runs the same digest verification + scrub pass, so a bundle
  rotted on disk between visits surfaces as a structured
  ``CheckpointCorruptError`` / ``FactorCorruptError``, never garbage X.

Evictions feed ``slu_fleet_handle_evictions_total`` (obs/metrics.py).
docs/SERVING.md's fleet chapter walks the tier.
"""

from __future__ import annotations

import collections

from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.utils.errors import SuperLUError
from superlu_dist_tpu.utils.lockwatch import make_condition, make_lock


class _Entry:
    __slots__ = ("key", "path", "server", "nbytes")

    def __init__(self, key, path, server, nbytes):
        self.key = key
        self.path = path
        self.server = server
        self.nbytes = int(nbytes)


class HandleCache:
    """LRU of factored serve handles, keyed by the caller's matrix key
    and backed by persist bundles.

    Parameters
    ----------
    budget_bytes : int
        Resident-handle byte budget; None reads
        ``SLU_TPU_FLEET_HANDLE_BYTES`` (0 = unbounded).
    server_kw : dict
        Extra ``SolveServer`` constructor keywords for every loaded
        handle (e.g. ``max_wait_s=0.0`` for the fleet's deterministic
        one-request batches).
    """

    def __init__(self, budget_bytes: int | None = None,
                 server_kw: dict | None = None):
        from superlu_dist_tpu.utils.options import env_int
        if budget_bytes is None:
            budget_bytes = env_int("SLU_TPU_FLEET_HANDLE_BYTES")
        self.budget_bytes = int(budget_bytes)
        self._server_kw = dict(server_kw or {})
        self._lock = make_lock("HandleCache._lock")
        self._cond = make_condition("HandleCache._cond", self._lock)
        self._paths: dict = {}                      # key -> bundle dir
        self._entries = collections.OrderedDict()   # key -> _Entry (LRU)
        self._loading: set = set()
        self._bytes = 0
        self._loads = 0
        self._hits = 0
        self._evictions = 0
        self._closed = False
        m = get_metrics()
        self._metrics = m if m.enabled else None

    # ------------------------------------------------------------------
    def register(self, key, bundle_path: str) -> dict:
        """Bind ``key`` to a persist bundle and return its manifest
        meta (the lu_meta cheap peek — validates the manifest and
        prices the handle without reading an array).  Re-registering a
        key (a deploy) re-points FUTURE loads; an already resident
        handle keeps serving until swapped or evicted."""
        from superlu_dist_tpu.persist.serial import lu_meta
        meta = lu_meta(str(bundle_path))      # validates + prices
        with self._lock:
            self._paths[key] = str(bundle_path)
        return meta

    def path(self, key) -> str:
        with self._lock:
            return self._paths[key]

    def keys(self) -> list:
        """Registered keys (resident or not)."""
        with self._lock:
            return list(self._paths)

    def resident(self) -> list:
        """Keys currently holding a loaded server, LRU order."""
        with self._lock:
            return list(self._entries)

    # ------------------------------------------------------------------
    def get(self, key):
        """The server for ``key`` — a cache hit refreshes its LRU slot;
        a miss loads the registered bundle zero-refactor, evicting idle
        least-recently-used handles past the byte budget first, and
        scrub-verifies the freshly resident factors before returning.
        Concurrent getters of the same key coalesce onto one load."""
        while True:
            with self._lock:
                if self._closed:
                    raise SuperLUError("HandleCache is closed")
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    self._hits += 1
                    return ent.server
                if key in self._loading:
                    self._cond.wait(0.05)
                    continue
                path = self._paths.get(key)
                if path is None:
                    raise SuperLUError(
                        f"handle key {key!r} is not registered with "
                        "this cache (register(key, bundle_path) first)")
                self._loading.add(key)
                break
        try:
            server, nbytes = self._load(key, path)
        except BaseException:
            with self._lock:
                self._loading.discard(key)
                self._cond.notify_all()
            raise
        with self._lock:
            self._loading.discard(key)
            self._entries[key] = _Entry(key, path, server, nbytes)
            self._bytes += nbytes
            self._loads += 1
            self._cond.notify_all()
        return server

    def _load(self, key, path):
        """Outside the lock (bundle I/O + digest work must never stall
        submit-side cache hits — the SLU109 hold discipline): price the
        handle off the manifest, make room, load, scrub-verify."""
        from superlu_dist_tpu.persist.serial import lu_meta
        from superlu_dist_tpu.serve.server import SolveServer
        nbytes = int(lu_meta(path).get("nbytes", 0))
        with get_tracer().span("handle-load", cat="request",
                               key=str(key), nbytes=nbytes):
            self._evict_for(nbytes)
            server = SolveServer.from_bundle(path, **self._server_kw)
            # scrub-verified (re)load: the resident panel stacks must
            # match the bundle manifest's sha256 ground truth BEFORE
            # serving (raises FactorCorruptError + quarantine on
            # mismatch)
            server.scrub_now()
        return server, nbytes

    def _evict_for(self, incoming: int) -> int:
        """Evict idle LRU entries until ``incoming`` bytes fit the
        budget.  Busy servers are never evicted (tickets outlive
        handles, not the other way round) — when everything resident is
        busy the cache runs over budget instead of dropping work.
        Server shutdown happens OUTSIDE the lock (close joins
        threads)."""
        if self.budget_bytes <= 0:
            return 0
        victims = []
        with self._lock:
            while self._bytes + incoming > self.budget_bytes:
                victim = None
                for ent in self._entries.values():      # LRU order
                    if ent.server.idle():
                        victim = ent
                        break
                if victim is None:
                    break
                del self._entries[victim.key]
                self._bytes -= victim.nbytes
                victims.append(victim)
            self._evictions += len(victims)
        for ent in victims:
            ent.server.close(timeout=10.0)
        if victims and self._metrics is not None:
            self._metrics.inc("slu_fleet_handle_evictions_total",
                              float(len(victims)))
        return len(victims)

    def deploy(self, key, bundle_path: str) -> bool:
        """Re-point ``key`` to a new bundle and hot-swap the resident
        server if one is loaded (``SolveServer.swap`` — the
        digest-verified load, queued + future tickets on the new
        handle, the in-flight batch finishing on the old one, zero
        dropped; the scrub baseline re-bases to the new manifest).
        Returns True when a resident handle was actually swapped.  The
        swap's bundle I/O runs OUTSIDE the cache lock."""
        meta = self.register(key, bundle_path)
        with self._lock:
            ent = self._entries.get(key)
            server = ent.server if ent is not None else None
        if server is None:
            return False
        server.swap(str(bundle_path))
        nbytes = int(meta.get("nbytes", 0))
        with self._lock:
            ent2 = self._entries.get(key)
            if ent2 is ent:
                self._bytes += nbytes - ent.nbytes
                ent.nbytes = nbytes
                ent.path = str(bundle_path)
        return True

    def drop(self, key) -> bool:
        """Explicitly evict ``key``'s resident server (idle or not —
        the deploy path drains through ``SolveServer.swap`` instead, so
        this is for teardown/tests).  Returns True when something was
        resident."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent.nbytes
        if ent is None:
            return False
        ent.server.close(timeout=10.0)
        return True

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "registered": len(self._paths),
                "resident": len(self._entries),
                "resident_bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "loads": self._loads,
                "hits": self._hits,
                "evictions": self._evictions,
            }

    def close(self):
        with self._lock:
            self._closed = True
            servers = [ent.server for ent in self._entries.values()]
            self._entries.clear()
            self._bytes = 0
        for srv in servers:
            srv.close(timeout=10.0)
