"""Double-float (df64) arithmetic: the emulated-f64 building blocks for TPU
(SURVEY.md §7 hard part 1).  Accuracy gates are vs exact float64."""

import numpy as np
import jax.numpy as jnp
import pytest

from superlu_dist_tpu.ops.df64 import (
    two_sum, two_prod, df64_add, df64_mul, df64_from_f64, df64_to_f64,
    df64_matmul)


def test_two_sum_exact():
    a = jnp.float32(1.0)
    b = jnp.float32(1e-8)          # vanishes in plain f32 addition
    s, e = two_sum(a, b)
    assert float(s) == 1.0
    assert float(e) == pytest.approx(1e-8, rel=1e-6)


def test_two_prod_exact():
    rng = np.random.default_rng(0)
    a = rng.standard_normal(1000).astype(np.float32)
    b = rng.standard_normal(1000).astype(np.float32)
    p, e = two_prod(jnp.asarray(a), jnp.asarray(b))
    exact = a.astype(np.float64) * b.astype(np.float64)
    np.testing.assert_array_equal(
        np.asarray(p, dtype=np.float64) + np.asarray(e, dtype=np.float64),
        exact)            # error-free: bitwise exact in f64


def test_roundtrip_and_ops_precision():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(512)
    y = rng.standard_normal(512)
    dx, dy = df64_from_f64(jnp.asarray(x)), df64_from_f64(jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(df64_to_f64(dx)), x, rtol=2e-15)
    s = np.asarray(df64_to_f64(df64_add(dx, dy)))
    p = np.asarray(df64_to_f64(df64_mul(dx, dy)))
    np.testing.assert_allclose(s, x + y, rtol=1e-14, atol=1e-14)
    np.testing.assert_allclose(p, x * y, rtol=1e-13, atol=1e-13)


@pytest.mark.slow
def test_df64_matmul_beats_f32_by_orders():
    """Full df64 accuracy under jit.  XLA:CPU's instruction fusion breaks
    the error-free transforms (see ops/df64.py caveat), so the strict gate
    runs in a subprocess with that pass disabled — the configuration the
    module documents for CPU; eager/TPU paths don't need it."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np, jax.numpy as jnp
from superlu_dist_tpu.ops.df64 import df64_from_f64, df64_to_f64, df64_matmul
for m, k, n in [(16, 64, 16), (8, 256, 8)]:
    rng = np.random.default_rng(2)
    a = rng.standard_normal((m, k)); b = rng.standard_normal((k, n))
    ah, al = df64_from_f64(jnp.asarray(a))
    bh, bl = df64_from_f64(jnp.asarray(b))
    got = np.asarray(df64_to_f64(df64_matmul(ah, al, bh, bl)))
    err_df = np.abs(got - a @ b).max()
    err_f32 = np.abs(np.asarray(
        jnp.asarray(a, jnp.float32) @ jnp.asarray(b, jnp.float32)) - a @ b).max()
    assert err_df < 1e-11, (m, k, n, err_df)
    assert err_df < err_f32 / 1e4, (m, k, n, err_df, err_f32)
print("DF64 MATMUL OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=300,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 MATMUL OK" in res.stdout


def test_df64_matmul_eager_exact_in_process():
    """Eager-mode df64 ops are exact on any backend (no fusion)."""
    rng = np.random.default_rng(3)
    m = k = n = 8
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    from superlu_dist_tpu.ops.df64 import df64_add, df64_mul
    ah, al = df64_from_f64(jnp.asarray(a))
    bh, bl = df64_from_f64(jnp.asarray(b))
    ch = jnp.zeros((m, n), jnp.float32)
    cl = jnp.zeros((m, n), jnp.float32)
    for i in range(k):
        ai = (ah[:, i][:, None], al[:, i][:, None])
        bi = (bh[i, :][None, :], bl[i, :][None, :])
        ch, cl = df64_add((ch, cl), df64_mul(ai, bi))
    got = np.asarray(df64_to_f64((ch, cl)))
    assert np.abs(got - a @ b).max() < 1e-12


@pytest.mark.slow
def test_df64_factorization_end_to_end():
    """factor_dtype="df64": true ~2^-48 factors on an f32-only backend.

    Ill-conditioned system (geometric row scaling, kappa ~ 1e7), NO
    equilibration and NO refinement, x64 OFF (the TPU situation): the
    f32 factors bottom out ~1e-8 while df64 reaches ~1e-15 — and a
    requested float64 silently truncates to f32 without x64, which is
    exactly the gap this path closes.  Runs jitted, in a subprocess with
    the XLA:CPU fusion passes disabled (ops/df64.py caveat)."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
import superlu_dist_tpu.sparse.formats as fmts
from superlu_dist_tpu.utils.options import Options, IterRefine

a0 = poisson2d(8)
s = np.logspace(0, 7, a0.n_rows)
rows = np.repeat(np.arange(a0.n_rows), np.diff(a0.indptr))
a = fmts.SparseCSR(a0.n_rows, a0.n_cols, a0.indptr, a0.indices,
                   a0.data * s[rows])
xt = np.random.default_rng(0).standard_normal(a.n_rows)
b = a.matvec(xt)
opt = dict(equil=False, iter_refine=IterRefine.NOREFINE)
x32, _, _, i32 = slu.gssvx(Options(factor_dtype="float32", **opt), a, b)
r32 = np.linalg.norm(b - a.matvec(x32)) / np.linalg.norm(b)
xdf, ludf, _, idf = slu.gssvx(Options(factor_dtype="df64", **opt), a, b)
rdf = np.linalg.norm(b - a.matvec(xdf)) / np.linalg.norm(b)
assert i32 == 0 and idf == 0, (i32, idf)
assert ludf.numeric.on_host and ludf.numeric.dtype == np.float64
assert rdf < 1e-11, rdf
assert rdf < r32 / 1e3, (rdf, r32)

# generic dense-random system (no special structure to mask rounding in
# the elimination): the ~2^-48 claim must hold here too
from superlu_dist_tpu.models.gallery import random_sparse
g = random_sparse(40, density=0.15, seed=5)
xg = np.random.default_rng(1).standard_normal(g.n_rows)
bg = g.matvec(xg)
xd, _, _, ig = slu.gssvx(Options(factor_dtype="df64", **opt), g, bg)
rg = np.linalg.norm(bg - g.matvec(xd)) / np.linalg.norm(bg)
assert ig == 0 and rg < 1e-12, rg

# singularity localization parity with the fast path
import superlu_dist_tpu.sparse.formats as fmts
d = a0.to_dense()
d[7] = d[9]                       # exact linear dependence
idx = np.nonzero(d)
ip = np.zeros(a0.n_rows + 1, np.int64)
np.add.at(ip, idx[0] + 1, 1)
ip = np.cumsum(ip)
sing = fmts.SparseCSR(a0.n_rows, a0.n_cols, ip, idx[1].astype(np.int64),
                      d[idx])
xs, _, _, infos = slu.gssvx(
    Options(factor_dtype="df64", replace_tiny_pivot=False, **opt), sing,
    np.ones(a0.n_rows))
assert infos > 0, infos

print(f"DF64 FACTOR OK f32={r32:.2e} df64={rdf:.2e} generic={rg:.2e}")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 FACTOR OK" in res.stdout


@pytest.mark.slow
def test_df64_front_factor_vs_exact_lu():
    """Front-level pin: df64 partial factorization vs exact f64 LU of the
    same front — the ~2^-48 contract measured directly, including a
    1e7-dynamic-range front (subprocess, fusion passes disabled)."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
from superlu_dist_tpu.ops.df64 import df64_from_f64
from superlu_dist_tpu.numeric.df64_factor import df64_partial_front_factor

rng = np.random.default_rng(7)
for scale_pow, gate in ((0, 1e-13), (7, 1e-9)):
    m, w = 12, 8
    f = rng.standard_normal((m, m)) + 4.0 * np.eye(m)
    f *= np.logspace(0, scale_pow, m)[:, None]
    fh, fl = df64_from_f64(f)
    fn = jax.jit(lambda h, l: df64_partial_front_factor(
        h, l, jnp.float32(0.0), w))
    (gh, gl), flags = fn(fh, fl)
    got = np.asarray(gh, np.float64) + np.asarray(gl, np.float64)
    # exact f64 unpivoted partial LU reference
    ref = f.copy()
    for i in range(w):
        ref[i+1:, i] /= ref[i, i]
        ref[i+1:, i+1:] -= np.outer(ref[i+1:, i], ref[i, i+1:])
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < gate, (scale_pow, rel)
print("DF64 FRONT OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=600,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 FRONT OK" in res.stdout


@pytest.mark.slow
def test_df64_executor_cached_same_pattern():
    """SamePattern_SameRowPerm reuse hits ONE cached Df64Executor:
    refactoring new
    values on the same pattern+rowperm (the tier that reuses the plan)
    must not redo the host-side index prep
    (the reference keeps its schedules in LUstruct across SamePattern
    calls, SRC/pdgssvx.c:1132-1166).  Subprocess with the XLA:CPU fusion
    passes disabled (ops/df64.py caveat)."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import superlu_dist_tpu as slu
import superlu_dist_tpu.sparse.formats as fmts
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.numeric.df64_factor import get_df64_executor
from superlu_dist_tpu.utils.options import Options, Fact, IterRefine

a = poisson2d(9)
xt = np.random.default_rng(4).standard_normal(a.n_rows)
b = a.matvec(xt)
opt = dict(factor_dtype="df64", iter_refine=IterRefine.NOREFINE)
x0, lu, _, i0 = slu.gssvx(Options(**opt), a, b)
# the PRODUCTION path must have populated the cache already — a
# get_df64_executor call here would itself create-and-cache one and
# make the identity check below vacuous.  Assert via the public surface
# (cache size unchanged by the lookup), not the internal key layout.
n_cached = len(lu.plan._factor_fns)
assert n_cached >= 1
ex0 = get_df64_executor(lu.plan)
assert len(lu.plan._factor_fns) == n_cached   # lookup hit, no new entry
# same pattern, new values
a2 = fmts.SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices,
                    a.data * 3.0 + 0.01)
b2 = a2.matvec(xt)
x2, lu2, _, i2 = slu.gssvx(
    Options(fact=Fact.SamePattern_SameRowPerm, **opt), a2, b2, lu=lu)
assert i0 == 0 and i2 == 0, (i0, i2)
assert lu2.plan is lu.plan            # plan reused across the tier
assert get_df64_executor(lu2.plan) is ex0   # executor cache hit
r = np.linalg.norm(b2 - a2.matvec(x2)) / np.linalg.norm(b2)
assert r < 1e-12, r
print("DF64 CACHE OK", r)
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 CACHE OK" in res.stdout


@pytest.mark.slow
def test_df64_sharded_matches_single_device():
    """df64 over a mesh (batch sharded on "snode") must equal the
    single-device result bitwise — sharding a vmapped elimination cannot
    perturb the error-free transforms.  Subprocess: virtual 8-device CPU
    mesh + the fusion passes disabled."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.parallel.grid import gridinit
from superlu_dist_tpu.utils.options import Options, IterRefine

a = poisson2d(11)
xt = np.random.default_rng(2).standard_normal(a.n_rows)
b = a.matvec(xt)
opt = dict(factor_dtype="df64", iter_refine=IterRefine.NOREFINE)
x0, lu0, _, i0 = slu.gssvx(Options(**opt), a, b)
grid = gridinit(4, 2)
x1, lu1, _, i1 = slu.gssvx(Options(**opt), a, b, grid=grid)
assert i0 == 0 and i1 == 0
for (lp0, up0), (lp1, up1) in zip(lu0.numeric.fronts, lu1.numeric.fronts):
    np.testing.assert_array_equal(lp0, lp1)
    np.testing.assert_array_equal(up0, up1)
np.testing.assert_array_equal(x0, x1)
r = np.linalg.norm(b - a.matvec(x1)) / np.linalg.norm(b)
assert r < 1e-12, r
print("DF64 SHARDED OK", r)
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 SHARDED OK" in res.stdout


@pytest.mark.slow
def test_df64_pool_partition_matches_replicated():
    """df64 with the hi/lo Schur pools PARTITIONED 1-D over the mesh must
    equal the replicated-pool mesh result bitwise (the same guarantee
    tests/test_pool_partition.py pins for the f32 path): sharding the
    pool scatter/gathers cannot change which summands reach an entry or
    their order, so the error-free transforms are untouched.  This is the
    path that takes the emulated-f64 tier to the n≈1M class whose pool
    exceeds one chip (VERDICT r3 missing #4)."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.parallel.grid import gridinit
from superlu_dist_tpu.utils.options import Options, IterRefine

a = poisson2d(11)
xt = np.random.default_rng(2).standard_normal(a.n_rows)
b = a.matvec(xt)
grid = gridinit(4, 2)
opt = dict(factor_dtype="df64", iter_refine=IterRefine.NOREFINE)
x0, lu0, _, i0 = slu.gssvx(Options(**opt), a, b, grid=grid)
x1, lu1, _, i1 = slu.gssvx(Options(pool_partition=True, **opt), a, b,
                           grid=grid)
assert i0 == 0 and i1 == 0
for (lp0, up0), (lp1, up1) in zip(lu0.numeric.fronts, lu1.numeric.fronts):
    np.testing.assert_array_equal(lp0, lp1)
    np.testing.assert_array_equal(up0, up1)
np.testing.assert_array_equal(x0, x1)
r = np.linalg.norm(b - a.matvec(x1)) / np.linalg.norm(b)
assert r < 1e-12, r
print("DF64 POOLPART OK", r)
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "DF64 POOLPART OK" in res.stdout


@pytest.mark.slow
def test_df64_beats_f32_ir_at_kappa_1e10():
    """The df64 raison d'être: genuine spectral ill-conditioning at
    κ≈1e10, where f32 factors + f64 IR converge on the RESIDUAL but the
    SOLUTION is garbage (forward error ≈ κ·residual ~ 1e-1), while df64
    factors recover ~1e-9 forward error.  Near-singular shift A − σI with
    σ just below λ_min — diagonal scaling cannot manufacture this (LU is
    row-scale invariant) and equilibration cannot remove it.  Beyond
    κ≈1e11 the f64 residual itself limits every path (κ·ε₆₄·growth ≳
    1e-3 forward error) — that boundary is the reference's too."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
import superlu_dist_tpu.sparse.formats as fmts
from superlu_dist_tpu.utils.options import Options

a0 = poisson2d(16)                     # n = 256
n = a0.n_rows
rows = np.repeat(np.arange(n), np.diff(a0.indptr))
A = np.zeros((n, n))
A[rows, a0.indices] = a0.data
lam = np.linalg.eigvalsh(A)
lmin, lmax = lam[0], lam[-1]
delta = lmax / (lmin * 1e10)           # kappa(A - sigma I) ~ 1e10
sigma = lmin * (1 - delta)
vals = a0.data.copy()
vals[rows == a0.indices] -= sigma
a = fmts.SparseCSR(n, n, a0.indptr, a0.indices, vals)
xt = np.random.default_rng(0).standard_normal(n)
b = a.matvec(xt)

x32, _, st32, i32 = slu.gssvx(Options(factor_dtype="float32"), a, b)
e32 = np.linalg.norm(x32 - xt) / np.linalg.norm(xt)
xdf, _, stdf, idf = slu.gssvx(Options(factor_dtype="df64"), a, b)
edf = np.linalg.norm(xdf - xt) / np.linalg.norm(xt)
rdf = np.linalg.norm(b - a.matvec(xdf)) / np.linalg.norm(b)
assert i32 == 0 and idf == 0, (i32, idf)
assert e32 > 1e-3, e32       # f32+IR solution fails at this conditioning
assert edf < 1e-7, edf       # df64 recovers the solution
assert rdf < 1e-12, rdf
print(f"HIKAPPA OK f32_err={e32:.2e} df64_err={edf:.2e} df64_resid={rdf:.2e}")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "HIKAPPA OK" in res.stdout


def test_zdf64_ops_eager_accuracy():
    """Complex df64 algebra (zdf64_*): mul/div/add reach ~2^-48 relative
    accuracy in eager mode (exact EFTs), far beyond c64's 2^-24."""
    import numpy as np
    from superlu_dist_tpu.ops.df64 import (zdf64_add, zdf64_div, zdf64_mul,
                                           zdf64_from_c128, zdf64_to_c128)
    rng = np.random.default_rng(11)
    a = (rng.standard_normal(256) + 1j * rng.standard_normal(256)) \
        * np.exp(rng.uniform(-8, 8, 256))
    b = (rng.standard_normal(256) + 1j * rng.standard_normal(256)) \
        * np.exp(rng.uniform(-8, 8, 256))
    za, zb = zdf64_from_c128(a), zdf64_from_c128(b)
    # split roundtrip: ~2^-48 relative (the lo word is itself rounded
    # to f32, so the pair carries ~48 significant bits, not all 53)
    rel0 = np.abs(zdf64_to_c128(za) - a) / np.abs(a)
    assert rel0.max() < 1e-13, rel0.max()
    for op, ref in ((zdf64_add, a + b), (zdf64_mul, a * b),
                    (zdf64_div, a / b)):
        got = zdf64_to_c128(op(za, zb))
        rel = np.abs(got - ref) / np.maximum(np.abs(ref), 1e-300)
        assert rel.max() < 1e-13, (op.__name__, rel.max())


def test_zdf64_complex_factorization_end_to_end():
    """factor_dtype="df64" with COMPLEX input — the zdf64 twin of the
    reference's pzgstrf (SRC/pzgstrf.c:243), via the component-algebra
    template instead of twin files.  Ill-conditioned complex system
    (geometric row scaling, kappa ~ 1e7), no equilibration, no
    refinement, x64 OFF: the c64 factors bottom out ~1e-8 while zdf64
    reaches f64-class residuals.  Subprocess with XLA:CPU fusion passes
    disabled (ops/df64.py caveat)."""
    import os
    import subprocess
    import sys
    code = """
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_disable_hlo_passes=fusion,cpu-instruction-fusion"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
import superlu_dist_tpu.sparse.formats as fmts
from superlu_dist_tpu.utils.options import Options, IterRefine

a0 = poisson2d(8)
n = a0.n_rows
s = np.logspace(0, 7, n)
rows = np.repeat(np.arange(n), np.diff(a0.indptr))
theta = np.random.default_rng(3).uniform(0, 2 * np.pi, a0.nnz)
vals = a0.data * s[rows] * np.exp(1j * theta)
a = fmts.SparseCSR(n, n, a0.indptr, a0.indices, vals)
rng = np.random.default_rng(0)
xt = rng.standard_normal(n) + 1j * rng.standard_normal(n)
b = a.matvec(xt)
opt = dict(equil=False, iter_refine=IterRefine.NOREFINE)
x32, _, _, i32 = slu.gssvx(Options(factor_dtype="float32", **opt), a, b)
r32 = np.linalg.norm(b - a.matvec(x32)) / np.linalg.norm(b)
xdf, ludf, _, idf = slu.gssvx(Options(factor_dtype="df64", **opt), a, b)
rdf = np.linalg.norm(b - a.matvec(xdf)) / np.linalg.norm(b)
assert i32 == 0 and idf == 0, (i32, idf)
assert ludf.numeric.on_host and ludf.numeric.dtype == np.complex128
assert rdf < 1e-11, rdf
assert rdf < r32 / 1e3, (rdf, r32)
print(f"ZDF64 FACTOR OK c64={r32:.2e} zdf64={rdf:.2e}")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                         capture_output=True, text=True)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "ZDF64 FACTOR OK" in res.stdout
