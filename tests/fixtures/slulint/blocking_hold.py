"""SLU109 true-positive fixture (hold discipline): file I/O and a
TreeComm collective inside a held lock stall every contender — and the
collective can deadlock the whole rank fleet on one process's lock."""
import threading


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []

    def flush(self, path):
        with self._lock:
            with open(path, "w") as f:
                f.write(repr(self._events))

    def ship(self, tc, payload):
        with self._lock:
            return tc.bcast_any(payload)
